"""Checkpoint corruption: CRC32 verification, valid-step fallback,
CorruptCheckpointError semantics, write durability, async error
surfacing — the verified-checkpoint half of the self-healing story.

Covers truncate / zero-fill / delete-one-shard damage for BOTH on-disk
formats (single-file ``.npz`` and sharded), the ``latest_valid_step``
probe, ``restore_or_init`` fallback, and the CheckpointManager.close()
contract (a pending async write error surfaces instead of vanishing).
The multi-host broadcast path of the fallback
(``_agreed_latest_step``) runs in tests/test_two_process_corruption.py
(slow lane, real two-process cluster).
"""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.ckpt.checkpoint import (
    CheckpointManager, CorruptCheckpointError, restore_or_init)
from distributed_tensorflow_example_tpu.config import (MeshShape,
                                                       OptimizerConfig)
from distributed_tensorflow_example_tpu.models.mlp import MLP
from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh
from distributed_tensorflow_example_tpu.parallel.sharding import ShardingRules
from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
    SyncReplicas)
from distributed_tensorflow_example_tpu.runtime import faults
from distributed_tensorflow_example_tpu.train.optimizers import make_optimizer


def _state(v=0.0):
    return {"w": jnp.full((64,), v), "step": jnp.asarray(7, jnp.int32)}


def _truncate(path):
    with open(path, "r+b") as f:
        f.truncate(max(1, os.path.getsize(path) // 2))


def _zero_fill(path):
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 3)
        f.write(b"\0" * max(1, size // 3))


DAMAGE = {"truncate": _truncate, "zero": _zero_fill, "delete": os.remove}


# ---------------------------------------------------------------------------
# single-file format
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("damage", ["truncate", "zero", "delete"])
def test_corrupt_latest_falls_back_to_previous_valid(tmp_path, damage):
    mgr = CheckpointManager(str(tmp_path))
    for s in (1, 2, 3):
        mgr.save(_state(float(s)), step=s)
    DAMAGE[damage](mgr.checkpoint_path(3))
    assert mgr.latest_valid_step() == 2
    out = mgr.restore(_state(0.0))          # default step: newest VALID
    np.testing.assert_allclose(np.asarray(out["w"]), 2.0)


def test_explicit_corrupt_step_raises_named_error(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(1.0), step=5)
    _zero_fill(mgr.checkpoint_path(5))
    with pytest.raises(CorruptCheckpointError) as ei:
        mgr.restore(_state(0.0), step=5)
    msg = str(ei.value)
    assert "5" in msg and "ckpt-5.npz" in msg   # names step and file


def test_all_corrupt_raises_with_fallback_trail(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    for s in (1, 2):
        mgr.save(_state(float(s)), step=s)
    _truncate(mgr.checkpoint_path(1))
    _truncate(mgr.checkpoint_path(2))
    with pytest.raises(CorruptCheckpointError, match="no fallback"):
        mgr.restore(_state(0.0))
    assert mgr.latest_valid_step() is None


def test_crc_catches_zip_surviving_bitrot(tmp_path):
    """Flip bytes INSIDE an npy payload while keeping sizes intact: the
    zip layer may or may not notice, the recorded CRC32 must."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(1.0), step=1)
    path = mgr.checkpoint_path(1)
    data = bytearray(open(path, "rb").read())
    # flip a byte in the middle of the 'w' payload region
    probe = data.find(b"w.npy")
    assert probe != -1
    data[probe + 200] ^= 0xFF
    open(path, "wb").write(bytes(data))
    with pytest.raises(CorruptCheckpointError):
        mgr.restore(_state(0.0), step=1)


def test_restore_or_init_broadcast_path_falls_back(tmp_path):
    """Single-process _agreed_latest_step goes through latest_valid_step
    — restore_or_init must pick the valid step, not the corrupt latest."""
    mgr = CheckpointManager(str(tmp_path))
    for s in (1, 2):
        mgr.save(_state(float(s)), step=s)
    _truncate(mgr.checkpoint_path(2))
    state, restored = restore_or_init(mgr, lambda: _state(0.0))
    assert restored
    np.testing.assert_allclose(np.asarray(state["w"]), 1.0)


def test_pre_crc_checkpoints_still_restore(tmp_path):
    """Back-compat: a checkpoint written without the __crc32__ record
    (pre-verification format) loads — content-unverified but working."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(3.0), step=1)
    path = mgr.checkpoint_path(1)
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files if k != "__crc32__"}
    np.savez(path.replace(".npz", "") , **arrays)   # plain rewrite
    out = mgr.restore(_state(0.0), step=1)
    np.testing.assert_allclose(np.asarray(out["w"]), 3.0)


# ---------------------------------------------------------------------------
# sharded format
# ---------------------------------------------------------------------------

@pytest.fixture
def sharded_mgr(tmp_path):
    mesh = build_mesh(MeshShape(data=2, fsdp=4))
    model = MLP(in_dim=20, hidden=16, num_classes=4)
    tx = make_optimizer(OptimizerConfig(name="adam", learning_rate=0.1))
    sync = SyncReplicas(model.loss, tx, mesh,
                        rules=ShardingRules(fsdp_axis_size=4,
                                            fsdp_min_size=1))
    state = sync.init(model.init, seed=0)
    mgr = CheckpointManager(str(tmp_path), sharded=True)
    for s in (1, 2):
        mgr.save(state, step=s)
    return mgr, sync, state


@pytest.mark.parametrize("damage", ["truncate", "zero", "delete"])
def test_sharded_corrupt_shard_falls_back(sharded_mgr, damage):
    mgr, sync, state = sharded_mgr
    shards = sorted(glob.glob(os.path.join(mgr.directory,
                                           "ckpt-2.shard-*.npz")))
    assert shards
    DAMAGE[damage](shards[0])
    assert mgr.latest_valid_step() == 1
    out = mgr.restore(state)                 # falls back to step 1
    assert int(jax.device_get(out.step)) == int(jax.device_get(state.step))


def test_sharded_corrupt_anchor_falls_back(sharded_mgr):
    mgr, sync, state = sharded_mgr
    _truncate(mgr.shard_anchor_path(2))
    assert mgr.latest_valid_step() == 1


def test_sharded_explicit_step_raises_corrupt_error(sharded_mgr):
    mgr, sync, state = sharded_mgr
    for p in glob.glob(os.path.join(mgr.directory, "ckpt-2.shard-*.npz")):
        _zero_fill(p)
    with pytest.raises(CorruptCheckpointError):
        mgr.restore(state, step=2)


# ---------------------------------------------------------------------------
# write durability + async error surfacing (satellite: close() contract)
# ---------------------------------------------------------------------------

def test_async_save_error_surfaces_at_close(tmp_path):
    reg = faults.parse_spec("ckpt.write:step=1:raise=OSError")
    faults.install(reg)
    try:
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        mgr.save(_state(1.0), step=1)       # write fails on the thread
        with pytest.raises(OSError, match="injected fault"):
            mgr.close()
        # close() must still have released the executor despite raising
        assert mgr._executor._shutdown
    finally:
        faults.install(None)


def test_async_save_error_surfaces_at_next_save(tmp_path):
    reg = faults.parse_spec("ckpt.write:step=1:raise=OSError")
    faults.install(reg)
    try:
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        mgr.save(_state(1.0), step=1)
        with pytest.raises(OSError, match="injected fault"):
            mgr.save(_state(2.0), step=2)   # drain surfaces the error
        mgr.close()
    finally:
        faults.install(None)


def test_commit_fault_leaves_no_half_commit(tmp_path):
    """A crash between the data write and the state-file commit must not
    confuse restore: the state file never names the new step."""
    reg = faults.parse_spec("ckpt.commit:step=2:raise=OSError")
    faults.install(reg)
    try:
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_state(1.0), step=1)
        with pytest.raises(OSError):
            mgr.save(_state(2.0), step=2)
        assert mgr.latest_step() == 1        # uncommitted write invisible
        out = mgr.restore(_state(0.0))
        np.testing.assert_allclose(np.asarray(out["w"]), 1.0)
    finally:
        faults.install(None)


def test_injected_write_fault_then_clean_retry(tmp_path):
    """A failed synchronous save leaves the ring usable; a later save of
    the same step succeeds (no stale tmp files, no poisoned state file)."""
    reg = faults.parse_spec("ckpt.write:step=1:raise=OSError")
    faults.install(reg)
    try:
        mgr = CheckpointManager(str(tmp_path))
        with pytest.raises(OSError):
            mgr.save(_state(1.0), step=1)
        assert not glob.glob(os.path.join(str(tmp_path), "*.tmp"))
        mgr.save(_state(1.5), step=1)
        out = mgr.restore(_state(0.0), step=1)
        np.testing.assert_allclose(np.asarray(out["w"]), 1.5)
    finally:
        faults.install(None)
