"""Buffer donation of the compiled train step (sync_replicas donate).

The compiled step takes the whole TrainState and returns the next one;
without donation XLA must hold BOTH in memory across the dispatch —
params + optimizer state double-buffered in HBM (at the gate shapes
that's the difference between ~8.4 GiB peak and not fitting headroom
for anything else). ``SyncReplicas`` donates argument 0 by default;
these tests pin that contract via XLA's compiled-memory analysis so a
refactor that silently drops ``donate_argnums`` becomes a red test,
not a future OOM on chip.
"""

import jax
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import (OptimizerConfig,
                                                       TrainConfig)
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh
from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
    SyncReplicas)
from distributed_tensorflow_example_tpu.train.optimizers import (
    make_optimizer)


def _setup(donate: bool):
    cfg = TrainConfig(model="mlp",
                      optimizer=OptimizerConfig(name="adamw",
                                                learning_rate=1e-3))
    model = get_model("mlp", cfg)
    mesh = build_mesh()
    sync = SyncReplicas(model.loss, make_optimizer(cfg.optimizer), mesh,
                        donate=donate)
    state = sync.init(model.init, seed=0)
    batch = sync.shard_batch(model.dummy_batch(16))
    return sync, state, batch


def _tree_bytes(tree) -> int:
    return sum(int(np.dtype(l.dtype).itemsize * np.prod(l.shape))
               for l in jax.tree_util.tree_leaves(tree))


def test_step_donates_params_and_opt_state():
    """The compiled step's input/output aliasing must cover at least
    the params + optimizer state bytes — the double-buffering the
    donation exists to kill. Verified on the COMPILED executable
    (memory_analysis), not by reading the jit wrapper's kwargs."""
    sync, state, batch = _setup(donate=True)
    compiled = sync.step.lower(state, batch).compile()
    ma = compiled.memory_analysis()
    if isinstance(ma, (list, tuple)):
        ma = ma[0]
    aliased = int(ma.alias_size_in_bytes)
    want = _tree_bytes(state.params) + _tree_bytes(state.opt_state)
    assert aliased >= want, (
        f"compiled step aliases {aliased} bytes; params+opt_state are "
        f"{want} — donation is not reaching the executable")


def test_donate_false_control_buffers_both_states():
    """The control: with donation off the executable aliases nothing,
    so the donated build's memory win is attributable to
    donate_argnums (and the BASELINE.md peak-delta note has a measured
    basis)."""
    sync, state, batch = _setup(donate=False)
    compiled = sync.step.lower(state, batch).compile()
    ma = compiled.memory_analysis()
    if isinstance(ma, (list, tuple)):
        ma = ma[0]
    assert int(ma.alias_size_in_bytes) == 0


def test_donated_input_state_is_consumed():
    """Functional evidence on this backend: after a step, the donated
    input state's buffers are deleted — reading them raises instead of
    silently aliasing stale memory. (This is why call sites snapshot
    params before stepping, e.g. tests/test_self_healing.py.)"""
    sync, state, batch = _setup(donate=True)
    new_state, _ = sync.step(state, batch)
    jax.block_until_ready(new_state.params)
    leaf = jax.tree_util.tree_leaves(state.params)[0]
    with pytest.raises(RuntimeError):
        np.asarray(leaf)


def test_multi_step_donates_too():
    """The K-steps-per-dispatch loop carries the same state through K
    updates — double-buffering there would cost the same peak as the
    single step; it must alias as well."""
    cfg = TrainConfig(model="mlp",
                      optimizer=OptimizerConfig(name="adamw",
                                                learning_rate=1e-3))
    model = get_model("mlp", cfg)
    sync = SyncReplicas(model.loss, make_optimizer(cfg.optimizer),
                        build_mesh())
    state = sync.init(model.init, seed=0)
    host = model.dummy_batch(16)
    stacked = {k: np.stack([v, v]) for k, v in host.items()}
    placed = sync.shard_stacked_batch(stacked)
    compiled = sync.multi_step.lower(state, placed).compile()
    ma = compiled.memory_analysis()
    if isinstance(ma, (list, tuple)):
        ma = ma[0]
    want = _tree_bytes(state.params) + _tree_bytes(state.opt_state)
    assert int(ma.alias_size_in_bytes) >= want
