"""Fleet chaos gate (round 15): every seeded replica-level scenario
from experiments/fleet_chaos.py runs in tier-1 against one shared
export, plus the router-level seam-inertness parity regression (the
PR-9/PR-14 armed-vs-plain pattern extended to the new fleet seams).
The CLI soak is the slow-lane twin.
"""

import json
import os
import subprocess
import sys
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(ROOT, "experiments", "fleet_chaos.py")
sys.path.insert(0, os.path.join(ROOT, "experiments"))

import fleet_chaos  # noqa: E402
import serving_chaos  # noqa: E402

from distributed_tensorflow_example_tpu.runtime import faults  # noqa: E402


@pytest.fixture(scope="module")
def fleet_dir(tmp_path_factory):
    """ONE ample-pool paged export shared by every fleet scenario."""
    d = str(tmp_path_factory.mktemp("fleet"))
    vocab = serving_chaos.build_chaos_export(d, seed=0)
    return d, vocab


def _assert_ok(results):
    bad = [r for r in results if not r["ok"]]
    assert not bad, f"fleet scenario(s) failed: {bad}"


def test_fleet_kill_and_wedge(fleet_dir):
    """The acceptance core: killing or wedging one of three replicas
    mid-request yields ZERO client-visible failures and greedy bytes
    identical to an undisturbed single-replica run. Round 17: the
    wedge scenario additionally asserts (internally) that the stalled
    watchdog AUTO-wrote exactly one incident bundle
    (cause=watchdog_stall) whose registry snapshot matches the wedged
    replica's live /metrics page — without anyone arming tracing."""
    d, vocab = fleet_dir
    results = fleet_chaos.run_scenarios(
        ["kill_replica_mid_decode", "wedge_one_replica_watchdog"],
        seed=0, export_dir=d, vocab=vocab)
    _assert_ok(results)
    kill = results[0]
    assert kill["metrics"]["router_retries_total"] >= 1
    assert "incident bundle" in results[1]["detail"]
    assert "matches /metrics" in results[1]["detail"]


def test_fleet_breaker_trip_and_recover(fleet_dir):
    """The victim's breaker opens off the probe cadence and recovers
    via the half-open probe after a restart."""
    d, vocab = fleet_dir
    results = fleet_chaos.run_scenarios(
        ["breaker_trip_and_recover"], seed=0, export_dir=d,
        vocab=vocab)
    _assert_ok(results)
    assert results[0]["metrics"]["router_breaker_open_total"] >= 1
    # round 17: the router's flight recorder bundled the breaker-open
    # and replica-death incidents (rate-limited per cause)
    assert results[0]["metrics"]["router_incidents_total"] >= 2


def test_fleet_drain_under_load(fleet_dir):
    d, vocab = fleet_dir
    results = fleet_chaos.run_scenarios(
        ["drain_one_replica_under_load"], seed=0, export_dir=d,
        vocab=vocab)
    _assert_ok(results)
    assert results[0]["metrics"]["router_replica_healthy"] == 2


def test_fleet_hedge_cancels_loser(fleet_dir):
    """A hedged request's losing attempt is provably cancelled: the
    victim replica's blocks_free returns to baseline (asserted inside
    the scenario) and exactly one hedge was launched. Round 17 (the
    tracing acceptance core, asserted structurally inside the
    scenario via _assert_stitched_hedge): GET /trace/fleet yields ONE
    stitched Perfetto timeline in which the router's hedge span
    parents both replica attempts, each replica renders as its own
    clock-corrected process group, and the loser's cancellation span
    carries the same request id."""
    d, vocab = fleet_dir
    results = fleet_chaos.run_scenarios(
        ["hedge_cancels_loser"], seed=0, export_dir=d, vocab=vocab)
    _assert_ok(results)
    assert results[0]["metrics"]["router_hedges_total"] == 1
    assert results[0]["metrics"]["router_hedge_wins_total"] == 1
    assert "stitched fleet trace" in results[0]["detail"]
    assert "hedge parents both attempts" in results[0]["detail"]


# ---------------------------------------------------------------------------
# satellite: fleet seams join the armed-vs-plain inertness contract
# ---------------------------------------------------------------------------

def test_router_seams_inert_when_silent(fleet_dir):
    """A fault registry whose router.probe / router.forward /
    replica.crash rules never fire must leave the fleet byte-identical
    to no registry at all, with zero retries/hedges/breaker-opens —
    the armed-but-silent seams provably cost zero behavior."""
    d, vocab = fleet_dir
    prompts = serving_chaos.seeded_prompts(3, 17, vocab)

    def run(spec):
        if spec:
            faults.install(faults.parse_spec(spec, seed=0))
        try:
            fleet = fleet_chaos.make_fleet(d, 2)
            try:
                outs, _, errors = fleet_chaos._drive_wave(
                    fleet, prompts, max_new=3)
                assert not errors, errors
                met = fleet_chaos.router_counters(fleet)
                return outs, (met["router_retries_total"],
                              met["router_hedges_total"],
                              met["router_breaker_open_total"],
                              met["router_failovers_total"])
            finally:
                fleet.close()
        finally:
            faults.install(None)

    plain = run(None)
    armed = run("router.probe:step=999999;router.forward:step=999999;"
                "replica.crash:step=999999")
    assert plain == armed
    assert plain[1] == (0, 0, 0, 0)


def test_flight_recorder_off_is_byte_and_dispatch_identical(fleet_dir,
                                                            tmp_path):
    """The armed-vs-plain parity contract (round 17): a fleet with the
    flight recorder ON (always-on ring + incident_dir armed but QUIET —
    no failures) serves byte-identically to --flight_recorder off,
    with identical engine dispatch counts, and writes zero bundles.
    Observability must only ever ADD visibility, never behavior."""
    d, vocab = fleet_dir
    prompts = serving_chaos.seeded_prompts(3, 23, vocab)
    inc_dir = str(tmp_path / "incidents")

    def run(server_kw, router_kw):
        fleet = fleet_chaos.make_fleet(d, 2, server_kw=server_kw,
                                       **router_kw)
        try:
            # SEQUENTIAL requests: the idle least-outstanding
            # tie-break routes deterministically, so per-replica
            # dispatch counts are comparable across the two runs
            # (a concurrent wave's batching composition is
            # timing-dependent)
            outs = [fleet_chaos.router_post(
                fleet, p, max_new=3)["generations"][0]
                for p in prompts]
            dispatch = []
            for i in range(2):
                g = fleet_chaos.replica_stats(fleet, i)
                dispatch.append((g["decode_steps"], g["prefills"],
                                 g["requests_done"]))
            return outs, dispatch
        finally:
            fleet.close()

    armed = run({"incident_dir": inc_dir},
                {"incident_dir": inc_dir})
    plain = run({"flight_recorder": False},
                {"flight_recorder": False})
    assert armed[0] == plain[0], "flight recorder changed greedy bytes"
    assert armed[1] == plain[1], \
        "flight recorder changed dispatch counts"
    assert not (os.path.isdir(inc_dir) and os.listdir(inc_dir)), \
        "a quiet run wrote incident bundles"


@pytest.mark.slow
def test_fleet_chaos_cli_all_scenarios():
    """The registered slow gate: the full CLI soak in a fresh
    process."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    t0 = time.monotonic()
    out = subprocess.run(
        [sys.executable, SCRIPT, "--scenario", "all"],
        env=env, capture_output=True, text=True, timeout=1200,
        cwd=ROOT)
    rows = [json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")]
    assert rows, f"no output in {time.monotonic() - t0:.0f}s:\n" \
                 f"{out.stdout}\n{out.stderr[-2000:]}"
    assert out.returncode == 0, out.stderr[-2000:]
    summary = [r for r in rows if r.get("summary")][0]
    assert summary["failed"] == 0
    assert summary["scenarios"] == len(fleet_chaos.SCENARIOS)
