"""EP and PP collectives across a REAL process boundary (VERDICT r3
missing #2 / task #2).

``tests/_two_process_worker.py`` proved sync-DP + fsdp + sharded
checkpointing across two processes; this module boots the same kind of
2-process (4+4 virtual CPU devices) cluster with PERMUTED device meshes so
that the ``expert`` and ``pipe`` axes span the host boundary, making

- ``lax.all_to_all`` (MoE token exchange),
- ``lax.ppermute``  (GPipe stage hops), and
- ``lax.all_gather`` / ``lax.psum_scatter`` (Megatron-SP tensor
  parallelism inside PP×TP, with the ``model`` axis spanning hosts)

cross hosts in CI. The workers assert in-process that the axes really
cross (``_axis_crosses_hosts``) and that the hand-written all_to_all EP
path equals the dense-dispatch oracle; this module asserts the two
processes agree bitwise and that the cross-host runs match the
single-process 8-device runs on identical seeds/batches — the same
invariant the sync-DP leg asserts (rtol 1e-6: same HLO, but cross-host
collective reduction schedules are not guaranteed bit-identical).
"""

import os

import numpy as np
import pytest

from _cluster_harness import run_two_process

# multi-minute on the gate machine: a real two-process jax.distributed
# cluster spawn per test — the tier-1 fast lane (-m "not slow") skips
# these; the full suite remains the pre-ship gate
pytestmark = pytest.mark.slow

_DIR = os.path.dirname(os.path.abspath(__file__))
_WORKER = os.path.join(_DIR, "_two_process_ep_pp_worker.py")


@pytest.fixture(scope="module")
def ep_pp_result(tmp_path_factory):
    outdir = str(tmp_path_factory.mktemp("eppp"))
    run_two_process(_WORKER, [outdir], timeout=600)
    return outdir


def test_processes_agree_bitwise(ep_pp_result):
    z0 = np.load(os.path.join(ep_pp_result, "ep_pp_proc0.npz"))
    z1 = np.load(os.path.join(ep_pp_result, "ep_pp_proc1.npz"))
    assert set(z0.files) == set(z1.files)
    for k in z0.files:
        np.testing.assert_array_equal(z0[k], z1[k], err_msg=k)


def _single_process_reference():
    """The same EP and PP training runs on the single-process 8-device
    mesh (canonical device order): seeds and batches identical to the
    workers', so results must match."""
    import jax

    from distributed_tensorflow_example_tpu.config import (MeshShape,
                                                           OptimizerConfig,
                                                           TrainConfig)
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.models.moe import (MoeBert,
                                                               MoeBertConfig)
    from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
    from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
        SyncReplicas)
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)

    ref = {}

    mesh = local_mesh(8, {"data": 2, "expert": 4})
    cfg = MoeBertConfig.tiny()
    cfg.dropout = 0.0
    model = MoeBert(cfg)
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
    sync = SyncReplicas(model.loss, tx, mesh,
                        rules=model.sharding_rules(
                            MeshShape(data=2, expert=4)))
    state = sync.init(model.init, seed=11)
    batch = sync.shard_batch(model.dummy_batch(8))
    losses = []
    for _ in range(2):
        state, m = sync.step(state, batch)
        losses.append(float(jax.device_get(m["loss"])))
    ref["ep_losses"] = np.asarray(losses)
    ref["ep_params"] = [np.asarray(p) for p in
                        jax.tree_util.tree_leaves(
                            jax.device_get(state.params))]

    mesh = local_mesh(8, {"data": 2, "fsdp": 2, "pipe": 2})
    pmodel = get_model("pipe_bert_tiny", TrainConfig(model="pipe_bert_tiny"))
    pmodel.bind_mesh(mesh)
    ptx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
    psync = SyncReplicas(pmodel.loss, ptx, mesh,
                         rules=pmodel.sharding_rules(
                             MeshShape(data=2, fsdp=2, pipe=2)))
    pstate = psync.init(pmodel.init, seed=12)
    pbatch = psync.shard_batch(pmodel.dummy_batch(16))
    plosses = []
    for _ in range(2):
        pstate, m = psync.step(pstate, pbatch)
        plosses.append(float(jax.device_get(m["loss"])))
    ref["pp_losses"] = np.asarray(plosses)
    ref["pp_params"] = [np.asarray(p) for p in
                        jax.tree_util.tree_leaves(
                            jax.device_get(pstate.params))]

    mesh = local_mesh(8, {"data": 2, "model": 2, "pipe": 2})
    tmodel = get_model("pipe_bert_tiny", TrainConfig(model="pipe_bert_tiny"))
    tmodel.bind_mesh(mesh)
    tsync = SyncReplicas(tmodel.loss,
                         make_optimizer(OptimizerConfig(
                             name="sgd", learning_rate=0.1)),
                         mesh, rules=tmodel.sharding_rules(
                             MeshShape(data=2, model=2, pipe=2)))
    tstate = tsync.init(tmodel.init, seed=13)
    tbatch = tsync.shard_batch(tmodel.dummy_batch(16))
    tlosses = []
    for _ in range(2):
        tstate, m = tsync.step(tstate, tbatch)
        tlosses.append(float(jax.device_get(m["loss"])))
    ref["pptp_losses"] = np.asarray(tlosses)
    ref["pptp_params"] = [np.asarray(p) for p in
                          jax.tree_util.tree_leaves(
                              jax.device_get(tstate.params))]

    # EP x TP: MoeBert with expert weights on BOTH axes
    mesh = local_mesh(8, {"data": 2, "expert": 2, "model": 2})
    ecfg = MoeBertConfig.tiny()
    ecfg.dropout = 0.0
    emodel = MoeBert(ecfg)
    esync = SyncReplicas(emodel.loss,
                         make_optimizer(OptimizerConfig(
                             name="sgd", learning_rate=0.1)),
                         mesh, rules=emodel.sharding_rules(
                             MeshShape(data=2, expert=2, model=2)))
    estate = esync.init(emodel.init, seed=15)
    ebatch = esync.shard_batch(emodel.dummy_batch(8))
    elosses = []
    for _ in range(2):
        estate, m = esync.step(estate, ebatch)
        elosses.append(float(jax.device_get(m["loss"])))
    ref["eptp_losses"] = np.asarray(elosses)
    ref["eptp_params"] = [np.asarray(p) for p in
                          jax.tree_util.tree_leaves(
                              jax.device_get(estate.params))]

    # SP: causal ring attention over the seq axis
    from distributed_tensorflow_example_tpu.models.gpt import (GPT,
                                                               GPTConfig)
    from distributed_tensorflow_example_tpu.parallel.ring_attention import (
        make_ring_attention)
    mesh = local_mesh(8, {"data": 4, "seq": 2})
    gcfg = GPTConfig.tiny()
    gcfg.dropout = 0.0
    gmodel = GPT(gcfg, attention_fn=make_ring_attention(mesh, causal=True))
    gsync = SyncReplicas(gmodel.loss,
                         make_optimizer(OptimizerConfig(
                             name="sgd", learning_rate=0.1)),
                         mesh, rules=gmodel.sharding_rules(
                             MeshShape(data=4, seq=2)))
    gstate = gsync.init(gmodel.init, seed=14)
    gbatch = gsync.shard_batch(gmodel.dummy_batch(8))
    glosses = []
    for _ in range(2):
        gstate, m = gsync.step(gstate, gbatch)
        glosses.append(float(jax.device_get(m["loss"])))
    ref["sp_losses"] = np.asarray(glosses)
    ref["sp_params"] = [np.asarray(p) for p in
                        jax.tree_util.tree_leaves(
                            jax.device_get(gstate.params))]
    return ref


def test_cross_host_matches_single_process(ep_pp_result):
    z0 = np.load(os.path.join(ep_pp_result, "ep_pp_proc0.npz"))
    ref = _single_process_reference()
    np.testing.assert_allclose(z0["ep_losses"], ref["ep_losses"],
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(z0["pp_losses"], ref["pp_losses"],
                               rtol=1e-6, atol=1e-7)
    # params after 2 SGD steps: the PERMUTED device mesh changes the
    # collective reduction order vs the canonical single-process mesh, so
    # the parity bar is a tight allclose, not bit-equality. (SGD, not
    # adam: the attention k-bias gradient is pure numerical noise —
    # softmax scores are shift-invariant in it — and adam normalizes that
    # noise into visible updates that cannot agree across orders.)
    for i, want in enumerate(ref["ep_params"]):
        np.testing.assert_allclose(z0[f"ep_p{i}"], want, rtol=1e-5,
                                   atol=1e-6, err_msg=f"ep leaf {i}")
    for i, want in enumerate(ref["pp_params"]):
        np.testing.assert_allclose(z0[f"pp_p{i}"], want, rtol=1e-5,
                                   atol=1e-6, err_msg=f"pp leaf {i}")
    # PP x TP with cross-host TP collectives: tolerance matches the
    # single-process PP x TP parity bar (TP splits contractions)
    np.testing.assert_allclose(z0["pptp_losses"], ref["pptp_losses"],
                               rtol=1e-5, atol=1e-6)
    for i, want in enumerate(ref["pptp_params"]):
        np.testing.assert_allclose(z0[f"pptp_p{i}"], want, rtol=1e-4,
                                   atol=1e-5, err_msg=f"pptp leaf {i}")
    # EP x TP (both the token all_to_all AND the per-expert Megatron
    # psum cross hosts) and SP (causal ring attention's ppermute across
    # hosts): same parity bars as their collective families above
    np.testing.assert_allclose(z0["eptp_losses"], ref["eptp_losses"],
                               rtol=1e-5, atol=1e-6)
    for i, want in enumerate(ref["eptp_params"]):
        np.testing.assert_allclose(z0[f"eptp_p{i}"], want, rtol=1e-4,
                                   atol=1e-5, err_msg=f"eptp leaf {i}")
    np.testing.assert_allclose(z0["sp_losses"], ref["sp_losses"],
                               rtol=1e-5, atol=1e-6)
    for i, want in enumerate(ref["sp_params"]):
        np.testing.assert_allclose(z0[f"sp_p{i}"], want, rtol=1e-4,
                                   atol=1e-5, err_msg=f"sp leaf {i}")
