"""REST predict server (serving_http.py): the TF Serving API shape
over an exported servable — row and columnar requests, status probe,
input validation errors as 400s, and numerical agreement with the
offline servable."""

import json
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import TrainConfig
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.serving import (export_model,
                                                        serving_signature)
from distributed_tensorflow_example_tpu.serving_http import PredictServer


@pytest.fixture(scope="module")
def servable_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("servable"))
    m = get_model("mlp", TrainConfig(model="mlp"))
    out = m.init(jax.random.key(0))
    params, extras = out if isinstance(out, tuple) else (out, {})
    export_model(m, params, extras, d, platforms=("cpu",))
    feats = serving_signature(m.dummy_batch(3))
    want = np.asarray(m.apply(params, extras, feats, train=False)[0])
    return d, feats, want


def _post(port, name, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/{name}:predict",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_predict_instances_and_inputs(servable_dir):
    d, feats, want = servable_dir
    with PredictServer(d) as srv:
        x = np.asarray(feats["x"])
        # row format
        out = _post(srv.port, srv.name,
                    {"instances": [{"x": row.tolist()} for row in x]})
        np.testing.assert_allclose(np.asarray(out["predictions"]), want,
                                   rtol=1e-5, atol=1e-5)
        # columnar format
        out2 = _post(srv.port, srv.name, {"inputs": {"x": x.tolist()}})
        np.testing.assert_allclose(np.asarray(out2["predictions"]), want,
                                   rtol=1e-5, atol=1e-5)
        # bare rows work for single-input models
        out3 = _post(srv.port, srv.name, {"instances": x.tolist()})
        np.testing.assert_allclose(np.asarray(out3["predictions"]), want,
                                   rtol=1e-5, atol=1e-5)


def test_status_probe_and_unknown_paths(servable_dir):
    d, _, _ = servable_dir
    with PredictServer(d, name="mnist") as srv:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/models/mnist") as r:
            st = json.loads(r.read())
        assert st["model_version_status"][0]["state"] == "AVAILABLE"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/models/nope")
        assert e.value.code == 404


def test_bad_requests_are_400(servable_dir):
    d, feats, _ = servable_dir
    with PredictServer(d) as srv:
        for payload in (
                {},                                     # neither key
                {"instances": []},                      # empty
                {"instances": [{"y": [0.0]}]},          # wrong input name
                {"inputs": {"x": [[0.0, 1.0]]}},        # wrong shape
        ):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(srv.port, srv.name, payload)
            assert e.value.code == 400
            body = json.loads(e.value.read())
            assert "error" in body


def test_varying_batch_sizes_one_server(servable_dir):
    """Batch polymorphism reaches the wire: any instance count on the
    same running server."""
    d, feats, _ = servable_dir
    x = np.asarray(feats["x"])
    with PredictServer(d) as srv:
        for n in (1, 2, 3):
            out = _post(srv.port, srv.name,
                        {"inputs": {"x": x[:n].tolist()}})
            assert np.asarray(out["predictions"]).shape == (n, 10)


def test_server_fault_is_500_not_400(servable_dir):
    """Runtime failures on the server side (platform mismatch, OOM) are
    500s with a JSON error — never client-blaming 400s or dropped
    connections."""
    d, feats, _ = servable_dir
    with PredictServer(d) as srv:
        sig = srv.servable.input_signature

        class Boom:
            input_signature = sig
            meta = {"model": "boom"}

            def __call__(self, f):
                raise RuntimeError("backend exploded")

        srv.servable = Boom()
        x = np.asarray(feats["x"])
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.port, srv.name, {"inputs": {"x": x.tolist()}})
        assert e.value.code == 500
        assert "backend exploded" in json.loads(e.value.read())["error"]


def test_multi_input_model_over_rest(tmp_path):
    """BERT-family servables take several feature keys per instance —
    the row format zips them and the columnar format passes through."""
    d = str(tmp_path / "bert")
    m = get_model("bert_tiny", TrainConfig(model="bert_tiny"))
    out = m.init(jax.random.key(0))
    params, extras = out if isinstance(out, tuple) else (out, {})
    export_model(m, params, extras, d, platforms=("cpu",))
    feats = serving_signature(m.dummy_batch(2))
    want = np.asarray(m.apply(params, extras, feats, train=False)[0])
    with PredictServer(d) as srv:
        rows = [{k: np.asarray(v)[i].tolist() for k, v in feats.items()}
                for i in range(2)]
        out1 = _post(srv.port, srv.name, {"instances": rows})
        np.testing.assert_allclose(np.asarray(out1["predictions"]), want,
                                   rtol=1e-5, atol=1e-5)
        # bare (non-dict) instances are invalid for multi-input models
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.port, srv.name,
                  {"instances": [[1, 2, 3]]})
        assert e.value.code == 400


def test_static_artifact_serves_any_count_up_to_batch(tmp_path):
    """A static-batch servable (MoE fallback) serves 1..B instances via
    server-side padding + response truncation (VERDICT r3 weak #3);
    above B is a clear 400, not an opaque XLA 500. Truncated responses
    must equal the full-batch predictions row-for-row."""
    d = str(tmp_path / "moe")
    m = get_model("moe_bert_tiny", TrainConfig(model="moe_bert_tiny"))
    out = m.init(jax.random.key(0))
    params, extras = out if isinstance(out, tuple) else (out, {})
    export_model(m, params, extras, d, platforms=("cpu",), batch_size=4)
    feats = serving_signature(m.dummy_batch(4))
    with PredictServer(d) as srv:
        full = _post(srv.port, srv.name,
                     {"inputs": {k: np.asarray(v).tolist()
                                 for k, v in feats.items()}})
        assert len(full["predictions"]) == 4
        for n in (1, 2, 3):
            short = {k: np.asarray(v)[:n].tolist()
                     for k, v in feats.items()}
            got = _post(srv.port, srv.name, {"inputs": short})
            assert len(got["predictions"]) == n
            # row i of a padded request is computed on the same padded
            # batch layout only for row content; routing capacity is
            # per-batch, so compare against a fresh full-batch run of
            # the SAME first-row padding, i.e. self-consistency: resend
            # and expect identical output (deterministic executable)
            again = _post(srv.port, srv.name, {"inputs": short})
            assert got == again
        over = {k: np.concatenate([np.asarray(v)] * 2).tolist()
                for k, v in feats.items()}
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.port, srv.name, {"inputs": over})
        assert e.value.code == 400
        assert "static batch" in json.loads(e.value.read())["error"]
        # inputs disagreeing on instance count are a 400 too
        bad = {k: np.asarray(v)[: 1 + i].tolist()
               for i, (k, v) in enumerate(feats.items())}
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.port, srv.name, {"inputs": bad})
        assert e.value.code == 400
