"""REST predict server (serving_http.py): the TF Serving API shape
over an exported servable — row and columnar requests, status probe,
input validation errors as 400s, and numerical agreement with the
offline servable."""

import json
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import TrainConfig
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.serving import (export_model,
                                                        serving_signature)
from distributed_tensorflow_example_tpu.serving_http import PredictServer


@pytest.fixture(scope="module")
def servable_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("servable"))
    m = get_model("mlp", TrainConfig(model="mlp"))
    out = m.init(jax.random.key(0))
    params, extras = out if isinstance(out, tuple) else (out, {})
    export_model(m, params, extras, d, platforms=("cpu",))
    feats = serving_signature(m.dummy_batch(3))
    want = np.asarray(m.apply(params, extras, feats, train=False)[0])
    return d, feats, want


def _post(port, name, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/{name}:predict",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_predict_instances_and_inputs(servable_dir):
    d, feats, want = servable_dir
    with PredictServer(d) as srv:
        x = np.asarray(feats["x"])
        # row format
        out = _post(srv.port, srv.name,
                    {"instances": [{"x": row.tolist()} for row in x]})
        np.testing.assert_allclose(np.asarray(out["predictions"]), want,
                                   rtol=1e-5, atol=1e-5)
        # columnar format
        out2 = _post(srv.port, srv.name, {"inputs": {"x": x.tolist()}})
        np.testing.assert_allclose(np.asarray(out2["predictions"]), want,
                                   rtol=1e-5, atol=1e-5)
        # bare rows work for single-input models
        out3 = _post(srv.port, srv.name, {"instances": x.tolist()})
        np.testing.assert_allclose(np.asarray(out3["predictions"]), want,
                                   rtol=1e-5, atol=1e-5)


def test_status_probe_and_unknown_paths(servable_dir):
    d, _, _ = servable_dir
    with PredictServer(d, name="mnist") as srv:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/models/mnist") as r:
            st = json.loads(r.read())
        assert st["model_version_status"][0]["state"] == "AVAILABLE"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/models/nope")
        assert e.value.code == 404


def test_bad_requests_are_400(servable_dir):
    d, feats, _ = servable_dir
    with PredictServer(d) as srv:
        for payload in (
                {},                                     # neither key
                {"instances": []},                      # empty
                {"instances": [{"y": [0.0]}]},          # wrong input name
                {"inputs": {"x": [[0.0, 1.0]]}},        # wrong shape
        ):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(srv.port, srv.name, payload)
            assert e.value.code == 400
            body = json.loads(e.value.read())
            assert "error" in body


def test_varying_batch_sizes_one_server(servable_dir):
    """Batch polymorphism reaches the wire: any instance count on the
    same running server."""
    d, feats, _ = servable_dir
    x = np.asarray(feats["x"])
    with PredictServer(d) as srv:
        for n in (1, 2, 3):
            out = _post(srv.port, srv.name,
                        {"inputs": {"x": x[:n].tolist()}})
            assert np.asarray(out["predictions"]).shape == (n, 10)


def test_server_fault_is_500_not_400(servable_dir):
    """Runtime failures on the server side (platform mismatch, OOM) are
    500s with a JSON error — never client-blaming 400s or dropped
    connections."""
    d, feats, _ = servable_dir
    with PredictServer(d) as srv:
        sig = srv.servable.input_signature

        class Boom:
            input_signature = sig
            meta = {"model": "boom"}

            def __call__(self, f):
                raise RuntimeError("backend exploded")

        srv.servable = Boom()
        x = np.asarray(feats["x"])
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.port, srv.name, {"inputs": {"x": x.tolist()}})
        assert e.value.code == 500
        assert "backend exploded" in json.loads(e.value.read())["error"]

        # a ValueError FROM THE EXECUTABLE (jax.export raises ValueError
        # for a wrong-platform artifact) is still the server's fault —
        # it must not fall into the client-fault 400 bucket
        class BoomVE(Boom):
            def __call__(self, f):
                raise ValueError("platform mismatch")

        srv.servable = BoomVE()
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.port, srv.name, {"inputs": {"x": x.tolist()}})
        assert e.value.code == 500
        assert "platform mismatch" in json.loads(e.value.read())["error"]


def test_multi_input_model_over_rest(tmp_path):
    """BERT-family servables take several feature keys per instance —
    the row format zips them and the columnar format passes through."""
    d = str(tmp_path / "bert")
    m = get_model("bert_tiny", TrainConfig(model="bert_tiny"))
    out = m.init(jax.random.key(0))
    params, extras = out if isinstance(out, tuple) else (out, {})
    export_model(m, params, extras, d, platforms=("cpu",))
    feats = serving_signature(m.dummy_batch(2))
    want = np.asarray(m.apply(params, extras, feats, train=False)[0])
    with PredictServer(d) as srv:
        rows = [{k: np.asarray(v)[i].tolist() for k, v in feats.items()}
                for i in range(2)]
        out1 = _post(srv.port, srv.name, {"instances": rows})
        np.testing.assert_allclose(np.asarray(out1["predictions"]), want,
                                   rtol=1e-5, atol=1e-5)
        # bare (non-dict) instances are invalid for multi-input models
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.port, srv.name,
                  {"instances": [[1, 2, 3]]})
        assert e.value.code == 400


def test_static_artifact_serves_any_count_up_to_batch(tmp_path):
    """A static-batch servable (MoE fallback) serves 1..B instances via
    server-side padding + response truncation (VERDICT r3 weak #3);
    above B is a clear 400, not an opaque XLA 500. Truncated responses
    must equal the full-batch predictions row-for-row."""
    d = str(tmp_path / "moe")
    m = get_model("moe_bert_tiny", TrainConfig(model="moe_bert_tiny"))
    out = m.init(jax.random.key(0))
    params, extras = out if isinstance(out, tuple) else (out, {})
    export_model(m, params, extras, d, platforms=("cpu",), batch_size=4)
    feats = serving_signature(m.dummy_batch(4))
    with PredictServer(d) as srv:
        full = _post(srv.port, srv.name,
                     {"inputs": {k: np.asarray(v).tolist()
                                 for k, v in feats.items()}})
        assert len(full["predictions"]) == 4
        for n in (1, 2, 3):
            short = {k: np.asarray(v)[:n].tolist()
                     for k, v in feats.items()}
            got = _post(srv.port, srv.name, {"inputs": short})
            assert len(got["predictions"]) == n
            # the real claim (ADVICE r4): row i of the truncated
            # response equals row i of the LIVE model applied to the
            # batch the server actually built — first n real rows,
            # padded to B by repeating row 0 (a deterministic-but-wrong
            # pad/truncate would pass a resend-self-consistency check;
            # it cannot pass an independent oracle)
            padded = {k: np.concatenate(
                [np.asarray(v)[:n],
                 np.repeat(np.asarray(v)[:1], 4 - n, axis=0)])
                for k, v in feats.items()}
            want_n = np.asarray(
                m.apply(params, extras, padded, train=False)[0])[:n]
            np.testing.assert_allclose(
                np.asarray(got["predictions"]), want_n,
                rtol=1e-5, atol=1e-5)
        over = {k: np.concatenate([np.asarray(v)] * 2).tolist()
                for k, v in feats.items()}
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.port, srv.name, {"inputs": over})
        assert e.value.code == 400
        assert "static batch" in json.loads(e.value.read())["error"]
        # zero instances: the pad path would hand the static executable
        # an EMPTY batch (np.repeat of v[:1] on 0 rows is still 0 rows)
        # — must be rejected as a client fault, not surface as a 500
        empty = {k: np.asarray(v)[:0].tolist() for k, v in feats.items()}
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.port, srv.name, {"inputs": empty})
        assert e.value.code == 400   # JSON [] loses the tail shape, so
        # the per-instance shape check 400s it; the n == 0 guard itself
        # is reached when the tail shape survives (shaped empty arrays):
        with pytest.raises(ValueError, match="zero instances"):
            srv._feature_arrays(
                {"inputs": {k: np.asarray(v)[:0] for k, v in feats.items()}})
        # inputs disagreeing on instance count are a 400 too
        bad = {k: np.asarray(v)[: 1 + i].tolist()
               for i, (k, v) in enumerate(feats.items())}
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.port, srv.name, {"inputs": bad})
        assert e.value.code == 400


def _post_verb(port, name, verb, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/{name}:{verb}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_generate_route_round_trip(tmp_path):
    """REST :generate over a generator artifact: greedy tokens match the
    live generate; a sampled artifact takes an integer seed (server
    synthesizes the rng input) and is deterministic per seed; the wrong
    route on each artifact kind is a clear 400."""
    from distributed_tensorflow_example_tpu.serving import export_generator
    import jax.numpy as jnp
    m = get_model("gpt_tiny", TrainConfig(model="gpt_tiny"))
    out = m.init(jax.random.key(0))
    params = out[0] if isinstance(out, tuple) else out
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 1000, (2, 8), dtype=np.int32)

    d = str(tmp_path / "greedy")
    export_generator(m, params, d, prompt_len=8, max_new_tokens=5,
                     batch_size=2, platforms=("cpu",))
    with PredictServer(d) as srv:
        got = _post_verb(srv.port, srv.name, "generate",
                         {"inputs": {"input_ids": ids.tolist()}})
        want = np.asarray(m.generate(params, jnp.asarray(ids), 5))
        np.testing.assert_array_equal(np.asarray(got["generations"]), want)
        # a 1-row request rides the static-batch pad/truncate path
        one = _post_verb(srv.port, srv.name, "generate",
                         {"inputs": {"input_ids": ids[:1].tolist()}})
        np.testing.assert_array_equal(np.asarray(one["generations"]),
                                      want[:1])
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.port, srv.name,
                  {"inputs": {"input_ids": ids.tolist()}})
        assert e.value.code == 400
        assert ":generate" in json.loads(e.value.read())["error"]

    d2 = str(tmp_path / "sampled")
    export_generator(m, params, d2, prompt_len=8, max_new_tokens=5,
                     batch_size=2, temperature=0.8, top_p=0.95,
                     platforms=("cpu",))
    with PredictServer(d2) as srv:
        a = _post_verb(srv.port, srv.name, "generate",
                       {"inputs": {"input_ids": ids.tolist()}, "seed": 3})
        b = _post_verb(srv.port, srv.name, "generate",
                       {"inputs": {"input_ids": ids.tolist()}, "seed": 3})
        c = _post_verb(srv.port, srv.name, "generate",
                       {"inputs": {"input_ids": ids.tolist()}, "seed": 4})
        assert a == b
        assert a != c
        want = np.asarray(m.generate(params, jnp.asarray(ids), 5,
                                     temperature=0.8, top_p=0.95,
                                     rng=jax.random.key(3)))
        np.testing.assert_array_equal(np.asarray(a["generations"]), want)
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_verb(srv.port, srv.name, "generate",
                       {"inputs": {"input_ids": ids.tolist()},
                        "seed": "not-an-int"})
        assert e.value.code == 400


def test_generate_rng_honors_recorded_prng_impl(tmp_path):
    """The export records prng_impl; the server synthesizes the rng key
    under THAT impl, and a residual shape mismatch (legacy artifact +
    different server default) is a clear 400 naming both shapes — not
    the opaque executable 500 of ADVICE r5."""
    from distributed_tensorflow_example_tpu.serving import export_generator
    m = get_model("gpt_tiny", TrainConfig(model="gpt_tiny"))
    params = m.init(jax.random.key(0))
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 1000, (1, 6), dtype=np.int32)
    d = str(tmp_path / "sampled")
    export_generator(m, params, d, prompt_len=6, max_new_tokens=3,
                     batch_size=1, temperature=1.0, platforms=("cpu",))
    with PredictServer(d) as srv:
        assert srv.servable.meta["prng_impl"] == str(
            jax.random.key_impl(jax.random.key(0)))
        ok = _post_verb(srv.port, srv.name, "generate",
                        {"inputs": {"input_ids": ids.tolist()}, "seed": 1})
        assert np.asarray(ok["generations"]).shape == (1, 3)
        # simulate the mismatch: an artifact whose recorded impl yields
        # key data of a DIFFERENT shape than the exported signature
        # (e.g. legacy threefry artifact served by an rbg-default
        # process) — must be a 400 that names both shapes
        srv.servable.meta["prng_impl"] = "rbg"       # [4]-word key data
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_verb(srv.port, srv.name, "generate",
                       {"inputs": {"input_ids": ids.tolist()}, "seed": 1})
        assert e.value.code == 400
        msg = json.loads(e.value.read())["error"]
        assert "rng" in msg and "prng" in msg.lower()
        # bogus impl name in metadata is the server's fault: 500
        srv.servable.meta["prng_impl"] = "no-such-impl"
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_verb(srv.port, srv.name, "generate",
                       {"inputs": {"input_ids": ids.tolist()}, "seed": 1})
        assert e.value.code == 500


def test_predict_artifact_rejects_generate_route(servable_dir):
    d, feats, _ = servable_dir
    with PredictServer(d) as srv:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_verb(srv.port, srv.name, "generate",
                       {"inputs": {"x": np.asarray(feats["x"]).tolist()}})
        assert e.value.code == 400
        assert ":predict" in json.loads(e.value.read())["error"]


def test_generate_ragged_rejects_all_masked_row(tmp_path):
    """A prompt_mask row with zero real tokens would decode garbage with
    a 200 (the in-model check cannot run on a traced mask); the server
    holds the concrete mask and must 400 it."""
    from distributed_tensorflow_example_tpu.serving import export_generator
    m = get_model("gpt_tiny", TrainConfig(model="gpt_tiny"))
    out = m.init(jax.random.key(0))
    params = out[0] if isinstance(out, tuple) else out
    d = str(tmp_path / "ragged")
    export_generator(m, params, d, prompt_len=6, max_new_tokens=3,
                     batch_size=2, ragged=True, platforms=("cpu",))
    ids = np.zeros((2, 6), np.int32)
    good = np.asarray([[1, 1, 1, 0, 0, 0], [1, 1, 0, 0, 0, 0]])
    bad = np.asarray([[1, 1, 1, 0, 0, 0], [0, 0, 0, 0, 0, 0]])
    with PredictServer(d) as srv:
        ok = _post_verb(srv.port, srv.name, "generate",
                        {"inputs": {"input_ids": ids.tolist(),
                                    "prompt_mask": good.tolist()}})
        assert np.asarray(ok["generations"]).shape == (2, 3)
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_verb(srv.port, srv.name, "generate",
                       {"inputs": {"input_ids": ids.tolist(),
                                   "prompt_mask": bad.tolist()}})
        assert e.value.code == 400
        assert "real token" in json.loads(e.value.read())["error"]


def test_unknown_inputs_are_400(servable_dir):
    """An input key the artifact does not take must be rejected, not
    silently dropped — e.g. a prompt_mask POSTed to a non-ragged
    generator would otherwise be discarded and garbage decoded with a
    200."""
    d, feats, _ = servable_dir
    with PredictServer(d) as srv:
        x = np.asarray(feats["x"])
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.port, srv.name,
                  {"inputs": {"x": x.tolist(), "prompt_mask": [[1]]}})
        assert e.value.code == 400
        assert "unknown model inputs" in json.loads(e.value.read())["error"]
