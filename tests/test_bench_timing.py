"""robust_time (bench.py): the artifact-resistant measurement core the
driver's BENCH gate rests on. The tunnel artifact is always absurdly
fast, so the helper must take the slower pass, retry on physically
impossible or wildly disagreeing readings, and flag what it cannot fix.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench import robust_time


def _passes(seq):
    it = iter(seq)

    def timed_pass():
        return next(it)
    return timed_pass


def test_takes_slower_of_two_clean_passes():
    dt, suspect = robust_time(_passes([1.0, 1.1]), steps=10)
    assert dt == 1.1 and not suspect


def test_wild_disagreement_retries_then_settles():
    # first pair disagrees 100x (artifact), second pair is clean
    dt, suspect = robust_time(_passes([0.01, 1.0, 1.0, 1.05]), steps=10)
    assert dt == 1.05 and not suspect


def test_wild_disagreement_every_time_is_suspect():
    dt, suspect = robust_time(
        _passes([0.01, 1.0] * 3), steps=10)
    assert suspect and dt == 1.0


def test_impossible_mfu_retries_and_flags():
    # flops/peak chosen so a 0.001s run implies ~10x peak; clean run 0.1s
    kw = dict(steps=10, flops=1e9, peak=1e12, n_dev=1)
    # both passes corrupted every attempt -> suspect
    dt, suspect = robust_time(_passes([0.001, 0.001] * 3), **kw)
    assert suspect
    # corruption clears on the second attempt -> clean
    dt, suspect = robust_time(
        _passes([0.001, 0.001, 0.1, 0.11]), **kw)
    assert dt == pytest.approx(0.11) and not suspect


def test_no_flops_estimate_uses_disagreement_only():
    # identical-but-fast passes can't be flagged without a flops bound:
    # documented limitation — the helper still returns the measurement
    dt, suspect = robust_time(_passes([0.001, 0.001]), steps=10)
    assert dt == pytest.approx(0.001) and not suspect
