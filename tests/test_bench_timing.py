"""robust_time + median_repeats (bench.py): the artifact-resistant
measurement cores the driver's BENCH gate rests on. The tunnel artifact
is always absurdly fast, so robust_time must take the slower pass,
retry on physically impossible or wildly disagreeing readings, and flag
what it cannot fix; the decode row's median_repeats must publish the
median of >=5 repeats (immune to single-call outliers in either
direction), its spread, and a suspect flag when the median itself sits
below the physical floor.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench import median_repeats, robust_time


def _passes(seq):
    it = iter(seq)

    def timed_pass():
        return next(it)
    return timed_pass


def test_takes_slower_of_two_clean_passes():
    dt, suspect = robust_time(_passes([1.0, 1.1]), steps=10)
    assert dt == 1.1 and not suspect


def test_wild_disagreement_retries_then_settles():
    # first pair disagrees 100x (artifact), second pair is clean
    dt, suspect = robust_time(_passes([0.01, 1.0, 1.0, 1.05]), steps=10)
    assert dt == 1.05 and not suspect


def test_wild_disagreement_every_time_is_suspect():
    dt, suspect = robust_time(
        _passes([0.01, 1.0] * 3), steps=10)
    assert suspect and dt == 1.0


def test_impossible_mfu_retries_and_flags():
    # flops/peak chosen so a 0.001s run implies ~10x peak; clean run 0.1s
    kw = dict(steps=10, flops=1e9, peak=1e12, n_dev=1)
    # both passes corrupted every attempt -> suspect
    dt, suspect = robust_time(_passes([0.001, 0.001] * 3), **kw)
    assert suspect
    # corruption clears on the second attempt -> clean
    dt, suspect = robust_time(
        _passes([0.001, 0.001, 0.1, 0.11]), **kw)
    assert dt == pytest.approx(0.11) and not suspect


def test_no_flops_estimate_uses_disagreement_only():
    # identical-but-fast passes can't be flagged without a flops bound:
    # documented limitation — the helper still returns the measurement
    dt, suspect = robust_time(_passes([0.001, 0.001]), steps=10)
    assert dt == pytest.approx(0.001) and not suspect


def test_median_repeats_takes_the_median_and_reports_spread():
    """5 repeats with one slow and one fast outlier: the median is the
    honest middle reading and the spread names the worst deviation."""
    med, spread, suspect = median_repeats(
        _passes([1.0, 0.9, 1.1, 1.02, 0.98]), reps=5)
    assert med == 1.0 and not suspect
    assert spread == pytest.approx(0.1)


def test_median_repeats_shrugs_off_single_fast_artifact():
    """The tunnel's return-without-running artifact corrupts ONE call:
    a max-of-two estimate wobbles, the median of 5 does not."""
    med, spread, suspect = median_repeats(
        _passes([0.001, 1.0, 1.01, 0.99, 1.0]), reps=5)
    assert med == 1.0 and not suspect
    assert spread == pytest.approx(0.999)   # the outlier IS the spread


def test_median_repeats_floor_retries_then_settles():
    # whole first sample corrupted below the physical floor; the
    # second sample is honest
    med, spread, suspect = median_repeats(
        _passes([0.001] * 3 + [1.0, 1.05, 0.95]), reps=3, floor_s=0.5)
    assert med == 1.0 and not suspect


def test_median_repeats_persistently_impossible_is_suspect():
    med, spread, suspect = median_repeats(
        _passes([0.001] * 9), reps=3, floor_s=0.5, retries=3)
    assert suspect and med == 0.001


def test_median_repeats_validates_reps():
    with pytest.raises(ValueError, match="reps"):
        median_repeats(_passes([1.0]), reps=0)


def test_median_repeats_single_rep_off_tpu_mode():
    # the CPU-sanity config times one repeat with no floor: the value
    # passes through, spread 0, never suspect
    med, spread, suspect = median_repeats(_passes([0.7]), reps=1)
    assert med == 0.7 and spread == 0.0 and not suspect


def test_vs_baseline_excludes_suspect_measurements():
    """A corrupt (suspect-flagged) reading must not move the gate: the
    round-4 incident was a ResNet 'step' of 2.46 ms / 6.28 MFU through
    the tunnel inflating vs_baseline to 1.8x despite robust_time having
    FLAGGED it."""
    import importlib.util, os
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    base = {"mnist_mlp_eps_chip": 100.0, "resnet50_eps_chip": 100.0}
    clean = {"mnist_mlp_eps_chip": 110.0, "resnet50_eps_chip": 110.0}
    assert abs(bench.vs_baseline_geomean(clean, base) - 1.1) < 1e-9
    corrupt = dict(clean, resnet50_eps_chip=5000.0, resnet50_suspect=True)
    # the corrupt 50x reading is excluded; only mnist's 1.1 remains
    assert abs(bench.vs_baseline_geomean(corrupt, base) - 1.1) < 1e-9
    # all-suspect -> neutral 1.0, not a crash
    allbad = {"mnist_mlp_eps_chip": 5000.0, "mnist_mlp_suspect": True}
    assert bench.vs_baseline_geomean(allbad, base) == 1.0


# ---------------------------------------------------------------------------
# decode de-noising (round 6): the two-point device-component fit and
# the gate's preference for it over tunnel-jittered wall-clock
# ---------------------------------------------------------------------------

def test_decode_device_component_fit():
    """Synthetic generation times on the measured model gen_s =
    0.099 + 0.00084*new (BASELINE.md decode roofline): the fit must
    recover the slope (device ms/token) and intercept (call overhead)."""
    from bench import decode_device_component

    t128 = 0.099 + 0.00084 * 128
    t512 = 0.099 + 0.00084 * 512
    dev_ms, overhead_ms = decode_device_component(t128, t512, 128, 512)
    assert dev_ms == pytest.approx(0.84)
    assert overhead_ms == pytest.approx(99.0)


def test_decode_device_component_rejects_bad_lengths():
    from bench import decode_device_component

    with pytest.raises(ValueError, match="new_long > new_short"):
        decode_device_component(0.2, 0.2, 128, 128)


def test_decode_gate_prefers_device_component():
    """Once BOTH baseline and measurement carry the device component,
    the gpt_decode ratio rides it (inverted: ms, lower is faster) and
    tunnel jitter in wall-clock tokens/s cannot move the gate; without
    the baseline key the row falls back to wall-clock tokens/s."""
    from bench import vs_baseline_geomean

    base = {"gpt_decode_tokens_s_chip": 5000,
            "gpt_decode_device_token_ms": 0.84}
    # wall-clock halved by a tunnel hiccup, device component unchanged
    extra = {"gpt_decode_tokens_s_chip": 2500,
             "gpt_decode_device_token_ms": 0.84}
    assert vs_baseline_geomean(extra, base) == pytest.approx(1.0)
    # device component regresses 20% -> the gate sees it
    worse = dict(extra, gpt_decode_device_token_ms=1.05)
    assert vs_baseline_geomean(worse, base) == pytest.approx(0.8)
    # no device baseline yet -> wall-clock fallback (pre-re-base rounds)
    legacy_base = {"gpt_decode_tokens_s_chip": 5000}
    assert vs_baseline_geomean(extra, legacy_base) == pytest.approx(0.5)
    # suspect flag still excludes the row entirely
    sus = dict(extra, gpt_decode_suspect=True,
               gpt_decode_device_token_ms=0.001)
    assert vs_baseline_geomean(sus, base) == 1.0
    # a NEGATIVE slope (corrupt long leg that dodged the suspect flag)
    # must not reach the geomean as a negative ratio (NaN): the row
    # falls back to wall-clock
    neg = dict(extra, gpt_decode_device_token_ms=-0.2)
    assert vs_baseline_geomean(neg, base) == pytest.approx(0.5)
