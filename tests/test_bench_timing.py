"""robust_time (bench.py): the artifact-resistant measurement core the
driver's BENCH gate rests on. The tunnel artifact is always absurdly
fast, so the helper must take the slower pass, retry on physically
impossible or wildly disagreeing readings, and flag what it cannot fix.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench import robust_time


def _passes(seq):
    it = iter(seq)

    def timed_pass():
        return next(it)
    return timed_pass


def test_takes_slower_of_two_clean_passes():
    dt, suspect = robust_time(_passes([1.0, 1.1]), steps=10)
    assert dt == 1.1 and not suspect


def test_wild_disagreement_retries_then_settles():
    # first pair disagrees 100x (artifact), second pair is clean
    dt, suspect = robust_time(_passes([0.01, 1.0, 1.0, 1.05]), steps=10)
    assert dt == 1.05 and not suspect


def test_wild_disagreement_every_time_is_suspect():
    dt, suspect = robust_time(
        _passes([0.01, 1.0] * 3), steps=10)
    assert suspect and dt == 1.0


def test_impossible_mfu_retries_and_flags():
    # flops/peak chosen so a 0.001s run implies ~10x peak; clean run 0.1s
    kw = dict(steps=10, flops=1e9, peak=1e12, n_dev=1)
    # both passes corrupted every attempt -> suspect
    dt, suspect = robust_time(_passes([0.001, 0.001] * 3), **kw)
    assert suspect
    # corruption clears on the second attempt -> clean
    dt, suspect = robust_time(
        _passes([0.001, 0.001, 0.1, 0.11]), **kw)
    assert dt == pytest.approx(0.11) and not suspect


def test_no_flops_estimate_uses_disagreement_only():
    # identical-but-fast passes can't be flagged without a flops bound:
    # documented limitation — the helper still returns the measurement
    dt, suspect = robust_time(_passes([0.001, 0.001]), steps=10)
    assert dt == pytest.approx(0.001) and not suspect


def test_vs_baseline_excludes_suspect_measurements():
    """A corrupt (suspect-flagged) reading must not move the gate: the
    round-4 incident was a ResNet 'step' of 2.46 ms / 6.28 MFU through
    the tunnel inflating vs_baseline to 1.8x despite robust_time having
    FLAGGED it."""
    import importlib.util, os
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    base = {"mnist_mlp_eps_chip": 100.0, "resnet50_eps_chip": 100.0}
    clean = {"mnist_mlp_eps_chip": 110.0, "resnet50_eps_chip": 110.0}
    assert abs(bench.vs_baseline_geomean(clean, base) - 1.1) < 1e-9
    corrupt = dict(clean, resnet50_eps_chip=5000.0, resnet50_suspect=True)
    # the corrupt 50x reading is excluded; only mnist's 1.1 remains
    assert abs(bench.vs_baseline_geomean(corrupt, base) - 1.1) < 1e-9
    # all-suspect -> neutral 1.0, not a crash
    allbad = {"mnist_mlp_eps_chip": 5000.0, "mnist_mlp_suspect": True}
    assert bench.vs_baseline_geomean(allbad, base) == 1.0
