"""Quantized decode end-to-end (round 12): int8 weights + int8 paged
KV-cache pool.

- quantize_kv_rows unit properties (error bound, zero rows,
  determinism — the prefix-cache byte-identity foundation),
- paged decode attention over int8 pools: XLA gather path vs a manual
  dequant of the same pools, Pallas scalar-prefetch kernel (interpret
  mode on CPU) vs the gather path, and loud scale/pool validation,
- model level: paged_prefill / decode_step_batched_paged
  quantize-on-write (written bytes exactly quantize_kv_rows of the
  float row; dead-row gating leaves pool AND scale bytes alone),
- export level: quant metadata recording, loud knob validation,
  pool_bytes sizing (int8 holds exactly 2x the bf16 block count at
  equal pool bytes — the capacity acceptance unit test), the quant-off
  bitwise no-op, and validate_quant_meta regressions naming the
  offending export.json field,
- engine + HTTP level: int8 greedy drift vs the full-precision oracle
  within the documented bound, prefix-cache reuse on int8 blocks,
  /stats kv_cache_dtype, and the serving_quant_fallback_total counter
  for pre-quant artifacts.
"""

import dataclasses
import json
import os
import sys
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import TrainConfig
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.models.gpt import (GPT, GPTConfig,
                                                           quantize_kv_rows)
from distributed_tensorflow_example_tpu.ops.pallas.decode_attention import (
    paged_decode_attention, paged_tile_friendly)
from distributed_tensorflow_example_tpu.serving import (ServableModel,
                                                        export_generator,
                                                        load_stepwise,
                                                        validate_quant_meta)
from distributed_tensorflow_example_tpu.serving_batch import (
    BlockPool, GenerationEngine)
from distributed_tensorflow_example_tpu.serving_http import PredictServer

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "experiments"))
from serving_load import INT8_MIN_AGREEMENT, token_agreement  # noqa: E402

PROMPT_LEN = 8
MAX_NEW = 5
SLOTS = 4
BLOCK = 4


# ---------------------------------------------------------------------------
# quantizer unit
# ---------------------------------------------------------------------------

def test_quantize_kv_rows_error_bound_and_zero_rows():
    """|x - q*s| <= s/2 per element (round-to-nearest symmetric int8),
    an all-zero row dequantizes to EXACT zeros (eps floor, no NaN),
    and the bytes are a pure function of the row values — the
    property prefix-cache block sharing rides."""
    rs = np.random.RandomState(0)
    x = rs.randn(3, 7, 4, 16).astype(np.float32)
    x[1, 2] = 0.0                              # an all-zero row
    q, s = quantize_kv_rows(jnp.asarray(x))
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    assert q.shape == x.shape and s.shape == (3, 7)
    deq = np.asarray(q, np.float32) * np.asarray(s)[..., None, None]
    err = np.abs(deq - x)
    assert (err <= np.asarray(s)[..., None, None] / 2 + 1e-7).all()
    np.testing.assert_array_equal(deq[1, 2], np.zeros((4, 16)))
    q2, s2 = quantize_kv_rows(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(q), np.asarray(q2))
    np.testing.assert_array_equal(np.asarray(s), np.asarray(s2))


# ---------------------------------------------------------------------------
# kernel / op level
# ---------------------------------------------------------------------------

def _quantized_pool(rs, n, bs, h, d):
    kf = rs.randn(n, bs, h, d).astype(np.float32)
    q, s = quantize_kv_rows(jnp.asarray(kf))
    return np.asarray(q), np.asarray(s)


def test_int8_paged_xla_matches_manual_dequant():
    """The XLA gather path's fused dequant == dequantizing the pools
    up front and running the float gather path, bit for bit."""
    rs = np.random.RandomState(1)
    b, h, d, bs, nb = 3, 4, 32, 4, 3
    n = 1 + b * nb
    kq, ks = _quantized_pool(rs, n, bs, h, d)
    vq, vs = _quantized_pool(rs, n, bs, h, d)
    q = rs.randn(b, h, d).astype(np.float32)
    bt = rs.permutation(np.arange(1, n))[:b * nb].reshape(b, nb)
    bt = bt.astype(np.int32)
    pos = np.array([2, 7, 11], np.int32)
    pad = np.array([0, 1, 0], np.int32)
    got = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kq), jnp.asarray(vq),
        block_tables=bt, pos=pos, pad=pad, k_scale=jnp.asarray(ks),
        v_scale=jnp.asarray(vs), impl="xla")
    kf = (kq.astype(np.float32) * ks[..., None, None]).astype(np.float32)
    vf = (vq.astype(np.float32) * vs[..., None, None]).astype(np.float32)
    want = paged_decode_attention(jnp.asarray(q), jnp.asarray(kf),
                                  jnp.asarray(vf), block_tables=bt,
                                  pos=pos, pad=pad, impl="xla")
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_int8_paged_kernel_matches_gather_reference():
    """The scalar-prefetch kernel's ALGEBRAIC dequant (scales folded
    into score columns / probabilities) vs the gather path, interpret
    mode on CPU — tier-1 covers both int8 impls (CI satellite)."""
    rs = np.random.RandomState(2)
    b, h, d, bs, nb = 2, 2, 64, 128, 3
    assert paged_tile_friendly(bs, d)
    n = 1 + b * nb
    kq, ks = _quantized_pool(rs, n, bs, h, d)
    vq, vs = _quantized_pool(rs, n, bs, h, d)
    q = rs.randn(b, h, d).astype(np.float32)
    bt = np.arange(1, 1 + b * nb, dtype=np.int32).reshape(b, nb)
    bt[0, 2] = 0                    # beyond pos: never read
    pos = np.array([130, 380], np.int32)
    pad = np.array([3, 0], np.int32)
    kw = dict(block_tables=bt, pos=pos, pad=pad,
              k_scale=jnp.asarray(ks), v_scale=jnp.asarray(vs))
    want = paged_decode_attention(jnp.asarray(q), jnp.asarray(kq),
                                  jnp.asarray(vq), impl="xla", **kw)
    got = paged_decode_attention(jnp.asarray(q), jnp.asarray(kq),
                                 jnp.asarray(vq), impl="pallas", **kw)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=2e-5, atol=2e-5)


def test_int8_paged_scale_validation():
    """Scales and int8 pools travel together — one without the other
    (or mis-shaped) is a loud error, never a silent garbage read."""
    rs = np.random.RandomState(3)
    b, h, d, bs, nb = 1, 2, 32, 4, 2
    n = 1 + b * nb
    kq, ks = _quantized_pool(rs, n, bs, h, d)
    q = jnp.asarray(rs.randn(b, h, d).astype(np.float32))
    bt = np.arange(1, 1 + nb, dtype=np.int32).reshape(b, nb)
    pos = np.zeros(b, np.int32)
    pad = np.zeros(b, np.int32)
    kw = dict(block_tables=bt, pos=pos, pad=pad)
    kqj, ksj = jnp.asarray(kq), jnp.asarray(ks)
    with pytest.raises(ValueError, match="together"):
        paged_decode_attention(q, kqj, kqj, k_scale=ksj, **kw)
    with pytest.raises(ValueError, match="k_scale/v_scale"):
        paged_decode_attention(q, kqj, kqj, **kw)
    with pytest.raises(ValueError, match="int8 pools"):
        kf = jnp.asarray(kq.astype(np.float32))
        paged_decode_attention(q, kf, kf, k_scale=ksj, v_scale=ksj,
                               **kw)
    with pytest.raises(ValueError, match="scale shape"):
        paged_decode_attention(q, kqj, kqj, k_scale=ksj[:, :2],
                               v_scale=ksj, **kw)


# ---------------------------------------------------------------------------
# model level: quantize-on-write
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def one_layer_model():
    """layers=1 makes the written K/V rows independent of the cache
    path (qkv is computed BEFORE attention), so quantize-on-write can
    be asserted byte-exact against quantize_kv_rows of the float
    path's own written row."""
    m = GPT(dataclasses.replace(GPTConfig.tiny(), layers=1))
    out = m.init(jax.random.key(0))
    params = out[0] if isinstance(out, tuple) else out
    return m, params


def test_paged_prefill_int8_writes_quantized_blocks(one_layer_model):
    """int8 paged_prefill == float paged_prefill + quantize_kv_rows of
    every written token row, byte for byte — and the logits (computed
    before any cache read) are identical."""
    m, params = one_layer_model
    c = m.cfg
    l, h, d = c.layers, c.heads, m.head_dim
    rs = np.random.RandomState(4)
    p = 6
    ids = np.zeros((1, PROMPT_LEN), np.int32)
    mask = np.zeros((1, PROMPT_LEN), np.int32)
    ids[0, :p] = rs.randint(0, c.vocab_size, (p,))
    mask[0, :p] = 1
    tr = np.array([2, 4], np.int32)
    zf = jnp.zeros((l, 6, BLOCK, h, d), jnp.float32)
    zq = jnp.zeros((l, 6, BLOCK, h, d), jnp.int8)
    zs = jnp.zeros((l, 6, BLOCK), jnp.float32)
    lg_f, kf, vf = m.paged_prefill(params, jnp.asarray(ids),
                                   jnp.asarray(mask), zf, zf,
                                   jnp.asarray(tr))
    lg_q, kq, vq, ksc, vsc = m.paged_prefill(
        params, jnp.asarray(ids), jnp.asarray(mask), zq, zq,
        jnp.asarray(tr), k_scale=zs, v_scale=zs)
    np.testing.assert_array_equal(np.asarray(lg_f), np.asarray(lg_q))
    for fp, qp, sp in ((kf, kq, ksc), (vf, vq, vsc)):
        wq, ws = quantize_kv_rows(np.asarray(fp)[:, tr])
        np.testing.assert_array_equal(np.asarray(qp)[:, tr],
                                      np.asarray(wq))
        np.testing.assert_array_equal(np.asarray(sp)[:, tr],
                                      np.asarray(ws))
    # determinism: a second prefill of the same tokens produces the
    # same bytes — what lets the prefix cache share int8 blocks
    _, kq2, _, ksc2, _ = m.paged_prefill(
        params, jnp.asarray(ids), jnp.asarray(mask), zq, zq,
        jnp.asarray(tr), k_scale=zs, v_scale=zs)
    np.testing.assert_array_equal(np.asarray(kq), np.asarray(kq2))
    np.testing.assert_array_equal(np.asarray(ksc), np.asarray(ksc2))


def test_paged_decode_step_int8_write_and_dead_row_gating(
        one_layer_model):
    """The int8 decode step quantizes its new row on write (bytes ==
    quantize_kv_rows of the float path's written row) and a dead row
    leaves pool AND scale bytes untouched."""
    m, params = one_layer_model
    c = m.cfg
    l, h, d = c.layers, c.heads, m.head_dim
    rs = np.random.RandomState(5)
    b, bs, nb = 2, 4, 2
    n = 1 + b * nb
    stacked = m.stack_decode_params(params)
    bt = (1 + np.arange(b * nb).reshape(b, nb)).astype(np.int32)
    # seed the pools with an already-quantized history
    hist = rs.randn(l, n, bs, h, d).astype(np.float32)
    q, s = quantize_kv_rows(jnp.asarray(hist))
    pools_f = {"k": jnp.asarray(np.asarray(q, np.float32)
                                * np.asarray(s)[..., None, None]),
               "v": jnp.asarray(np.asarray(q, np.float32)
                                * np.asarray(s)[..., None, None])}
    pools_q = {"k": q, "v": q, "k_scale": s, "v_scale": s}
    tok = jnp.asarray(rs.randint(0, c.vocab_size, (b,)), jnp.int32)
    pos = jnp.asarray([2, 5], jnp.int32)
    pad = jnp.zeros((b,), jnp.int32)
    alive = jnp.asarray([1, 0], jnp.int32)     # row 1 is DEAD
    _, new_f = m.decode_step_batched(
        params, stacked,
        {x: jnp.asarray(np.asarray(pools_f[x])[:, bt].reshape(
            l, b, nb * bs, h, d)) for x in ("k", "v")},
        tok, pos, pad, alive, decode_attention="xla")
    lg_q, new_q = m.decode_step_batched_paged(
        params, stacked, pools_q, bt, tok, pos, pad, alive,
        decode_attention="xla")
    assert lg_q.shape == (b, c.vocab_size)
    # live row 0: written bytes == quantize of the float path's row
    pb, off = bt[0, int(pos[0]) // bs], int(pos[0]) % bs
    for x, sx in (("k", "k_scale"), ("v", "v_scale")):
        row_f = np.asarray(new_f[x])[:, 0, int(pos[0])]     # [L, H, D]
        wq, ws = quantize_kv_rows(jnp.asarray(row_f))
        np.testing.assert_array_equal(
            np.asarray(new_q[x])[:, pb, off], np.asarray(wq))
        np.testing.assert_array_equal(
            np.asarray(new_q[sx])[:, pb, off], np.asarray(ws))
    # dead row 1: every one of its table's blocks byte-identical
    for x in ("k", "v", "k_scale", "v_scale"):
        np.testing.assert_array_equal(
            np.asarray(new_q[x])[:, bt[1]],
            np.asarray(pools_q[x])[:, bt[1]])


# ---------------------------------------------------------------------------
# export level
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    m = get_model("gpt_tiny", TrainConfig(model="gpt_tiny"))
    out = m.init(jax.random.key(0))
    params = out[0] if isinstance(out, tuple) else out
    return m, params


def _export(m, params, d, **kw):
    kw.setdefault("prompt_len", PROMPT_LEN)
    kw.setdefault("max_new_tokens", MAX_NEW)
    kw.setdefault("batch_size", 1)
    kw.setdefault("platforms", ("cpu",))
    return export_generator(m, params, d, **kw)


def test_export_quant_knob_validation(tiny_model, tmp_path):
    m, params = tiny_model
    d = str(tmp_path / "x")
    with pytest.raises(ValueError, match="paged=True"):
        _export(m, params, d, ragged=True, stepwise=True,
                kv_cache_dtype="int8")
    with pytest.raises(ValueError, match="requires paged=True"):
        _export(m, params, d, ragged=True, stepwise=True,
                pool_bytes=1 << 20)
    with pytest.raises(ValueError, match="not both"):
        _export(m, params, d, ragged=True, stepwise=True, paged=True,
                block_size=BLOCK, num_blocks=48, pool_bytes=1 << 20)
    with pytest.raises(ValueError, match="weight_quant"):
        _export(m, params, d, weight_quant="int4")
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        _export(m, params, d, ragged=True, stepwise=True, paged=True,
                kv_cache_dtype="fp8")


@pytest.fixture(scope="module")
def int8_dir(tmp_path_factory, tiny_model):
    """One int8 paged export (int8 weights + int8 KV pool) shared by
    the metadata/engine/HTTP tests."""
    d = str(tmp_path_factory.mktemp("int8"))
    m, params = tiny_model
    _export(m, params, d, ragged=True, stepwise=True, slots=SLOTS,
            paged=True, block_size=BLOCK, num_blocks=48,
            weight_quant="int8", kv_cache_dtype="int8")
    return d


def test_int8_export_metadata_and_pool(int8_dir):
    with open(os.path.join(int8_dir, "export.json")) as f:
        meta = json.load(f)
    assert meta["quant_schema"] == 1
    assert meta["weight_quant"] == "int8"
    sm = meta["stepwise"]
    assert sm["kv_cache_dtype"] == "int8"
    assert sm["cache_dtype"] == "int8"
    l_, n, bs = sm["pool_shape"][0], sm["pool_shape"][1], \
        sm["pool_shape"][2]
    assert sm["kv_scale_shape"] == [l_, n, bs]
    assert sm["kv_scale_dtype"] == "float32"
    assert sm["block_bytes"] > 0
    sw = load_stepwise(int8_dir)
    assert sw.kv_cache_dtype == "int8"
    pool = sw.make_pool()
    assert set(pool) == {"cache_k", "cache_v", "cache_k_scale",
                         "cache_v_scale"}
    assert pool["cache_k"].dtype == jnp.int8
    assert pool["cache_k_scale"].dtype == jnp.float32
    assert pool["cache_k_scale"].shape == (l_, n, bs)


def test_equal_pool_bytes_int8_doubles_blocks(tiny_model, tmp_path):
    """THE capacity acceptance unit test: at the same pool_bytes
    budget, the int8 export holds exactly 2x the bf16 usable block
    count (itemsize 2 -> 1), and BlockPool.from_bytes mirrors the
    sizing rule."""
    m, params = tiny_model
    budget = 1 << 20
    counts = {}
    for dtype in ("bf16", "int8"):
        d = str(tmp_path / dtype)
        _export(m, params, d, ragged=True, stepwise=True, slots=SLOTS,
                paged=True, block_size=BLOCK, pool_bytes=budget,
                kv_cache_dtype=dtype)
        sm = load_stepwise(d).step_meta
        counts[dtype] = int(sm["num_blocks"]) - 1       # minus null
    assert counts["int8"] == 2 * counts["bf16"]
    assert counts["int8"] >= 2                          # non-trivial
    bp = BlockPool.from_bytes(budget, 1024)
    assert bp.usable == budget // 1024
    with pytest.raises(ValueError, match="block_bytes"):
        BlockPool.from_bytes(budget, 0)


def test_block_pool_tracks_peak_in_use():
    bp = BlockPool(6)
    run = bp.alloc(3)
    assert bp.in_use == 3 and bp.peak_in_use == 3
    bp.release(run)
    assert bp.in_use == 0 and bp.peak_in_use == 3       # high-water
    bp.alloc(2)
    assert bp.peak_in_use == 3
    bp.alloc(2)
    assert bp.peak_in_use == 4


def test_quant_off_is_bitwise_noop(tiny_model, tmp_path):
    """weight_quant='off' + kv_cache_dtype='auto' normalize to the
    EXACT default export: same greedy bytes from the monolithic
    artifact, same pool dtype/bytes from the stepwise pair."""
    m, params = tiny_model
    rs = np.random.RandomState(6)
    ids = rs.randint(0, 1000, (1, PROMPT_LEN), dtype=np.int32)
    mask = np.ones_like(ids)
    outs, metas = [], []
    for name, kw in (("default", {}),
                     ("off", {"weight_quant": "off",
                              "kv_cache_dtype": "auto"})):
        d = str(tmp_path / name)
        _export(m, params, d, ragged=True, stepwise=True, slots=2,
                paged=True, block_size=BLOCK, num_blocks=24, **kw)
        sv = ServableModel(d)
        outs.append(np.asarray(sv({"input_ids": ids,
                                   "prompt_mask": mask})))
        metas.append(sv.meta)
    np.testing.assert_array_equal(outs[0], outs[1])
    for m0 in metas:
        assert m0["weight_quant"] is None
        assert m0["stepwise"]["kv_cache_dtype"] == \
            m0["stepwise"]["cache_dtype"]
        assert "kv_scale_shape" not in m0["stepwise"]
    assert metas[0]["stepwise"]["pool_shape"] == \
        metas[1]["stepwise"]["pool_shape"]
    sw = load_stepwise(str(tmp_path / "off"))
    assert set(sw.make_pool()) == {"cache_k", "cache_v"}


# ---------------------------------------------------------------------------
# metadata hardening
# ---------------------------------------------------------------------------

def _int8_meta():
    return {
        "quant_schema": 1, "weight_quant": "int8",
        "stepwise": {"paged": True, "kv_cache_dtype": "int8",
                     "cache_dtype": "int8",
                     "pool_shape": [2, 9, 4, 4, 32],
                     "kv_scale_shape": [2, 9, 4],
                     "kv_scale_dtype": "float32"}}


def test_validate_quant_meta_regressions():
    validate_quant_meta(_int8_meta())                   # the good case
    validate_quant_meta({})                             # pre-quant: ok
    m = _int8_meta()
    m["quant_schema"] = 99
    with pytest.raises(ValueError, match="quant_schema"):
        validate_quant_meta(m)
    m = _int8_meta()
    m["weight_quant"] = "int4"
    with pytest.raises(ValueError, match="weight_quant"):
        validate_quant_meta(m)
    m = _int8_meta()
    m["stepwise"]["paged"] = False
    with pytest.raises(ValueError, match="paged"):
        validate_quant_meta(m)
    m = _int8_meta()
    m["stepwise"]["kv_scale_shape"] = [2, 9, 8]
    with pytest.raises(ValueError, match="kv_scale_shape"):
        validate_quant_meta(m)
    m = _int8_meta()
    m["stepwise"]["kv_scale_dtype"] = "notadtype"
    with pytest.raises(ValueError, match="kv_scale_dtype"):
        validate_quant_meta(m)
    m = _int8_meta()
    m["stepwise"]["kv_cache_dtype"] = "alsonotadtype"
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        validate_quant_meta(m)


def test_loader_rejects_corrupt_quant_meta(int8_dir, tmp_path):
    """The loaders validate at LOAD time and the error names the
    artifact field — no shape error deep in the scan."""
    import shutil
    d = str(tmp_path / "corrupt")
    shutil.copytree(int8_dir, d)
    p = os.path.join(d, "export.json")
    with open(p) as f:
        meta = json.load(f)
    meta["stepwise"]["kv_scale_shape"] = [1, 2, 3]
    with open(p, "w") as f:
        json.dump(meta, f)
    with pytest.raises(ValueError, match="kv_scale_shape"):
        load_stepwise(d)
    with pytest.raises(ValueError, match="kv_scale_shape"):
        ServableModel(d)
    meta["quant_schema"] = 99
    with open(p, "w") as f:
        json.dump(meta, f)
    with pytest.raises(ValueError, match="quant_schema"):
        load_stepwise(d)


# ---------------------------------------------------------------------------
# engine + HTTP level
# ---------------------------------------------------------------------------

def _oracle(m, params, prompt, max_new=MAX_NEW):
    ids = np.zeros((1, PROMPT_LEN), np.int32)
    mask = np.zeros((1, PROMPT_LEN), np.int32)
    ids[0, :prompt.size] = prompt
    mask[0, :prompt.size] = 1
    return np.asarray(m.generate(params, jnp.asarray(ids), max_new,
                                 prompt_mask=jnp.asarray(mask)))[0].tolist()


def _prompts(n, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 1000, (int(rs.randint(1, PROMPT_LEN + 1)),)
                       ).astype(np.int32) for _ in range(n)]


def test_engine_int8_drift_within_bound_and_stats(int8_dir, tiny_model):
    """Engine-level drift gate: int8 greedy token streams agree with
    the full-precision oracle at >= the documented bound, and /stats
    reports the quantized pool's dtype + residency peak."""
    m, params = tiny_model
    prompts = _prompts(SLOTS * 2, seed=20)
    eng = GenerationEngine(load_stepwise(int8_dir))
    assert eng.kv_cache_dtype == "int8"
    futs = [eng.submit(p) for p in prompts]
    eng.start()
    try:
        got = [f.result(timeout=120) for f in futs]
    finally:
        eng.close()
    want = [_oracle(m, params, p) for p in prompts]
    agreement = token_agreement([got], [want])
    assert agreement >= INT8_MIN_AGREEMENT, (
        f"int8 drift gate: agreement {agreement} < "
        f"{INT8_MIN_AGREEMENT}")
    s = eng.stats()
    assert s["kv_cache_dtype"] == "int8"
    assert s["bytes_resident_peak"] > 0


def test_engine_int8_prefix_reuse_stays_deterministic(int8_dir):
    """Quantize-on-write commutes with the prefix cache: an identical
    repeat exact-hits (ZERO new prefills) and replays the SAME tokens
    — shared int8 blocks mount byte-identically."""
    prompts = _prompts(3, seed=21)
    eng = GenerationEngine(load_stepwise(int8_dir))
    futs = [eng.submit(p) for p in prompts]
    eng.start()
    try:
        first = [f.result(timeout=120) for f in futs]
        pre = eng.prefills
        second = [eng.submit(p).result(timeout=120) for p in prompts]
    finally:
        eng.close()
    assert eng.prefills == pre, "repeat prompts must not prefill"
    assert first == second
    assert eng.stats()["prefix_cache_hits"] >= len(prompts)


def test_http_int8_generate_stats_and_metrics(int8_dir, tiny_model):
    """HTTP-level drift gate + observability: :generate over the int8
    artifact tracks the oracle within the bound, /stats carries
    kv_cache_dtype, and /metrics exposes the quant counters."""
    m, params = tiny_model
    prompts = _prompts(4, seed=22)
    with PredictServer(int8_dir) as srv:
        assert srv.scheduler == "on"
        got = []
        for p in prompts:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/models/{srv.name}"
                ":generate",
                data=json.dumps(
                    {"inputs": {"input_ids": [p.tolist()]}}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                got.append(json.loads(r.read())["generations"][0])
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/stats") as r:
            stats = json.loads(r.read())["generate"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics") as r:
            prom = r.read().decode()
    want = [_oracle(m, params, p) for p in prompts]
    agreement = token_agreement([got], [want])
    assert agreement >= INT8_MIN_AGREEMENT
    assert stats["kv_cache_dtype"] == "int8"
    assert "serving_quant_fallback_total 0" in prom
    assert "serving_kv_cache_bytes_per_token" in prom


def test_int8_bytes_per_token_below_bf16(tiny_model, tmp_path):
    """The residency observable: one cached token costs fewer bytes
    under int8 (payload halves vs bf16; the f32 scale rows cost
    2*L*4 of it back)."""
    m, params = tiny_model
    vals = {}
    for dtype in ("bf16", "int8"):
        d = str(tmp_path / dtype)
        _export(m, params, d, ragged=True, stepwise=True, slots=2,
                paged=True, block_size=BLOCK, num_blocks=24,
                kv_cache_dtype=dtype)
        eng = GenerationEngine(load_stepwise(d))
        vals[dtype] = eng.registry.snapshot()[
            "serving_kv_cache_bytes_per_token"]["value"]
        eng.close()
    assert vals["int8"] < vals["bf16"]


def test_quant_fallback_counter_on_prequant_artifact(tiny_model,
                                                     tmp_path):
    """An artifact exported before the quant schema (no quant_schema
    key) still serves, but serving_quant_fallback_total counts it —
    the operator-visible signal that no quantized path is active."""
    m, params = tiny_model
    d = str(tmp_path / "prequant")
    _export(m, params, d)
    p = os.path.join(d, "export.json")
    with open(p) as f:
        meta = json.load(f)
    del meta["quant_schema"]
    del meta["weight_quant"]
    with open(p, "w") as f:
        json.dump(meta, f)
    with PredictServer(d) as srv:
        snap = srv.registry.snapshot()
        assert snap["serving_quant_fallback_total"]["value"] == 1
    # a modern (schema-carrying) artifact does NOT count
    d2 = str(tmp_path / "modern")
    _export(m, params, d2)
    with PredictServer(d2) as srv:
        assert srv.registry.snapshot()[
            "serving_quant_fallback_total"]["value"] == 0


# ---------------------------------------------------------------------------
# CLI plumbing
# ---------------------------------------------------------------------------

def test_cli_gen_weight_quant_guarded_without_export():
    from distributed_tensorflow_example_tpu.cli.train import main
    with pytest.raises(SystemExit, match="gen_weight_quant"):
        main(["--model", "gpt_tiny", "--train_steps", "1",
              "--batch_size", "8", "--gen_weight_quant", "int8"])


def test_cli_gen_weight_quant_reaches_artifact(tmp_path):
    """--gen_weight_quant int8 lands in the exported artifact's quant
    metadata (the config→CLI plumbing, end to end)."""
    from distributed_tensorflow_example_tpu.cli.train import main
    d = str(tmp_path / "gen")
    rc = main(["--model", "gpt_tiny", "--train_steps", "2",
               "--batch_size", "8", "--export_generator", d,
               "--gen_prompt_len", "8", "--gen_max_new", "4",
               "--gen_weight_quant", "int8"])
    assert rc == 0
    with open(os.path.join(d, "export.json")) as f:
        meta = json.load(f)
    assert meta["weight_quant"] == "int8"
    assert meta["quant_schema"] == 1
