"""Sharded checkpointing (TF Saver ``sharded=True`` parity, SURVEY.md
§3.4/§5.4): per-process shard files, piece-wise selective restore, ring
rotation of whole shard sets, cross-format compatibility.

The true multi-process distribution of pieces is exercised by the
two-process cluster test (tests/_two_process_worker.py); here the piece
machinery runs single-process on the 8-device CPU mesh (process 0 owns
every piece but still writes them piece-per-device-shard).
"""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.ckpt.checkpoint import (
    CheckpointManager, restore_or_init)
from distributed_tensorflow_example_tpu.config import (MeshShape,
                                                       OptimizerConfig)
from distributed_tensorflow_example_tpu.models.mlp import MLP
from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh
from distributed_tensorflow_example_tpu.parallel.sharding import ShardingRules
from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
    SyncReplicas)
from distributed_tensorflow_example_tpu.train.optimizers import make_optimizer


@pytest.fixture
def sync_and_state():
    mesh = build_mesh(MeshShape(data=2, fsdp=4))
    model = MLP(in_dim=20, hidden=16, num_classes=4)
    tx = make_optimizer(OptimizerConfig(name="adam", learning_rate=0.1))
    sync = SyncReplicas(model.loss, tx, mesh,
                        rules=ShardingRules(fsdp_axis_size=4,
                                            fsdp_min_size=1))
    return sync, sync.init(model.init, seed=0)


def _assert_states_equal(a, b, check_sharding=True):
    fa = jax.tree_util.tree_flatten_with_path(a)[0]
    fb = jax.tree_util.tree_flatten_with_path(b)[0]
    for (path, la), (_, lb) in zip(fa, fb):
        if jax.dtypes.issubdtype(getattr(la, "dtype", np.float32),
                                 jax.dtypes.prng_key):
            assert jnp.array_equal(jax.random.key_data(la),
                                   jax.random.key_data(lb)), path
            continue
        assert jnp.array_equal(la, lb), path
        if check_sharding and isinstance(la, jax.Array):
            assert lb.sharding == la.sharding, path


def test_sharded_roundtrip_preserves_values_and_shardings(
        sync_and_state, tmp_path):
    sync, state = sync_and_state
    mgr = CheckpointManager(str(tmp_path), sharded=True)
    mgr.save(state, 5)
    files = sorted(os.path.basename(f)
                   for f in glob.glob(str(tmp_path / "*")))
    assert "ckpt-5.shards.json" in files
    assert any(f.startswith("ckpt-5.shard-0-of-") for f in files)
    assert not any(f.endswith("ckpt-5.npz") for f in files)
    restored = mgr.restore(jax.tree_util.tree_map(lambda x: x, state))
    _assert_states_equal(state, restored)


def test_sharded_pieces_are_actually_split(sync_and_state, tmp_path):
    """fsdp-sharded leaves must be stored as multiple pieces (that is the
    point: each piece can be written/read by its owner alone)."""
    sync, state = sync_and_state
    mgr = CheckpointManager(str(tmp_path), sharded=True)
    mgr.save(state, 1)
    [shard] = glob.glob(str(tmp_path / "ckpt-1.shard-*.npz"))
    with np.load(shard) as z:
        piece_keys = [k for k in z.files if "::" in k]
    # the fsdp=4 mesh splits at least the largest param leaves 4-ways
    by_leaf: dict = {}
    for k in piece_keys:
        by_leaf.setdefault(k.split("::")[0], []).append(k)
    assert any(len(v) >= 4 for v in by_leaf.values()), by_leaf


def test_ring_rotation_removes_all_shard_files(sync_and_state, tmp_path):
    sync, state = sync_and_state
    mgr = CheckpointManager(str(tmp_path), sharded=True, max_to_keep=2)
    for s in (1, 2, 3, 4):
        mgr.save(state, s)
    left = sorted(os.path.basename(f)
                  for f in glob.glob(str(tmp_path / "ckpt-*")))
    assert mgr.all_steps() == [3, 4]
    assert not any("ckpt-1" in f or "ckpt-2" in f for f in left), left


def test_restore_or_init_finds_sharded(sync_and_state, tmp_path):
    sync, state = sync_and_state
    mgr = CheckpointManager(str(tmp_path), sharded=True)
    state = state.replace(step=state.step + 7)
    mgr.save(state)
    restored, was_restored = restore_or_init(
        mgr, lambda: sync_and_state[0].init(MLP(20, 16, 4).init, seed=0))
    assert was_restored
    assert int(jax.device_get(restored.step)) == 7


def test_format_autodetect_across_modes(sync_and_state, tmp_path):
    """A manager in either mode restores checkpoints written by the other
    (the format is detected from what is on disk, per step)."""
    sync, state = sync_and_state
    CheckpointManager(str(tmp_path), sharded=True).save(state, 1)
    CheckpointManager(str(tmp_path), sharded=False).save(state, 2)
    plain = CheckpointManager(str(tmp_path))
    _assert_states_equal(
        state, plain.restore(jax.tree_util.tree_map(lambda x: x, state), 1))
    _assert_states_equal(
        state, plain.restore(jax.tree_util.tree_map(lambda x: x, state), 2))
    assert plain.all_steps() == [1, 2]


def test_same_step_format_switch_supersedes(sync_and_state, tmp_path):
    """Re-saving step N in the other format must evict the old anchor —
    a stale ckpt-N.npz may not shadow a newer ckpt-N.shards.json."""
    sync, state = sync_and_state
    CheckpointManager(str(tmp_path)).save(state, 5)
    marked = state.replace(params=jax.tree_util.tree_map(
        lambda x: x + 1 if x.dtype.kind == "f" else x, state.params))
    CheckpointManager(str(tmp_path), sharded=True).save(marked, 5)
    assert not os.path.exists(str(tmp_path / "ckpt-5.npz"))
    restored = CheckpointManager(str(tmp_path)).restore(
        jax.tree_util.tree_map(lambda x: x, state), 5)
    _assert_states_equal(marked, restored)
    # and the reverse direction evicts the shard set
    CheckpointManager(str(tmp_path)).save(state, 5)
    assert not os.path.exists(str(tmp_path / "ckpt-5.shards.json"))
    assert not glob.glob(str(tmp_path / "ckpt-5.shard-*.npz"))


def test_latest_checkpoint_points_at_sharded_anchor(
        sync_and_state, tmp_path):
    from distributed_tensorflow_example_tpu.ckpt.checkpoint import (
        latest_checkpoint)
    sync, state = sync_and_state
    CheckpointManager(str(tmp_path), sharded=True).save(state, 9)
    p = latest_checkpoint(str(tmp_path))
    assert p is not None and p.endswith("ckpt-9.shards.json")
    assert os.path.exists(p)


def test_sharded_bf16_roundtrip(tmp_path):
    mesh = build_mesh(MeshShape(fsdp=8))
    model = MLP(in_dim=24, hidden=32, num_classes=4,
                param_dtype=jnp.bfloat16)
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
    sync = SyncReplicas(model.loss, tx, mesh,
                        rules=ShardingRules(fsdp_axis_size=8,
                                            fsdp_min_size=1))
    state = sync.init(model.init, seed=1)
    mgr = CheckpointManager(str(tmp_path), sharded=True)
    mgr.save(state, 3)
    restored = mgr.restore(jax.tree_util.tree_map(lambda x: x, state), 3)
    _assert_states_equal(state, restored)
    assert any(l.dtype == jnp.bfloat16
               for l in jax.tree_util.tree_leaves(restored.params))


def test_missing_shard_file_raises(sync_and_state, tmp_path):
    sync, state = sync_and_state
    mgr = CheckpointManager(str(tmp_path), sharded=True)
    mgr.save(state, 1)
    [shard] = glob.glob(str(tmp_path / "ckpt-1.shard-*.npz"))
    os.remove(shard)
    with pytest.raises(FileNotFoundError, match="shard"):
        mgr.restore(jax.tree_util.tree_map(lambda x: x, state), 1)


def test_resharding_restore_onto_different_mesh(tmp_path):
    """Save under fsdp=8, restore onto a data=2,fsdp=4 template: piece
    bounds no longer match the wanted shards, so the fallback assembles
    leaves from pieces — values must survive exactly."""
    model = MLP(in_dim=24, hidden=32, num_classes=4)
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
    s8 = SyncReplicas(model.loss, tx, build_mesh(MeshShape(fsdp=8)),
                      rules=ShardingRules(fsdp_axis_size=8, fsdp_min_size=1))
    state8 = s8.init(model.init, seed=2)
    mgr = CheckpointManager(str(tmp_path), sharded=True)
    mgr.save(state8, 1)

    s4 = SyncReplicas(model.loss, tx,
                      build_mesh(MeshShape(data=2, fsdp=4)),
                      rules=ShardingRules(fsdp_axis_size=4, fsdp_min_size=1))
    template = s4.init(model.init, seed=99)
    restored = mgr.restore(template, 1)
    _assert_states_equal(state8, restored, check_sharding=False)
    # and the restored copy carries the TEMPLATE's shardings
    for (path, t), (_, r) in zip(
            jax.tree_util.tree_flatten_with_path(template)[0],
            jax.tree_util.tree_flatten_with_path(restored)[0]):
        if isinstance(t, jax.Array) and not jax.dtypes.issubdtype(
                t.dtype, jax.dtypes.prng_key):
            assert r.sharding == t.sharding, path


def test_sharded_async_single_process(sync_and_state, tmp_path):
    """sharded+async is allowed single-process (no commit barrier needed):
    save returns immediately, wait() lands the write, restore sees it."""
    sync, state = sync_and_state
    mgr = CheckpointManager(str(tmp_path), sharded=True, async_save=True)
    mgr.save(state, 4)
    mgr.wait()
    assert os.path.exists(str(tmp_path / "ckpt-4.shards.json"))
    restored = mgr.restore(jax.tree_util.tree_map(lambda x: x, state), 4)
    _assert_states_equal(state, restored)
    mgr.close()


def test_sharded_roundtrip_randomized_pytrees(tmp_path):
    """Randomized structures: nested dicts/lists, f32/bf16/int leaves,
    scalars, odd host-local shapes — every leaf must survive the
    piece-wise roundtrip bit-exactly. (Uneven pieces cannot arise:
    jax.device_put rejects NamedShardings whose dim is not divisible by
    the mesh, so every distributed piece is equal-sized by construction —
    verified by attempting a (30, 3) placement over data=8.)"""
    from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
    from distributed_tensorflow_example_tpu.parallel.sharding import (
        batch_sharding)

    mesh = local_mesh(8, {"data": 8})
    rs = np.random.RandomState(0)
    for trial in range(3):
        tree = {
            "a": jnp.asarray(rs.randn(16, 24).astype(np.float32)),
            "nested": {
                "b16": jnp.asarray(rs.randn(8, 8).astype(np.float32),
                                   dtype=jnp.bfloat16),
                "ints": jnp.asarray(rs.randint(0, 9, (7,)),
                                    dtype=jnp.int32),
                "list": [jnp.float32(1.5), jnp.int32(trial)],
            },
            "sharded": jax.device_put(
                rs.randn(32, 5).astype(np.float32),
                batch_sharding(mesh)),
            "scalar": jnp.float32(rs.randn()),
        }
        if trial == 0:
            with pytest.raises(ValueError, match="divisible"):
                jax.device_put(rs.randn(30, 3).astype(np.float32),
                               batch_sharding(mesh))
        d = tmp_path / f"t{trial}"
        mgr = CheckpointManager(str(d), sharded=True)
        mgr.save(tree, trial)
        restored = mgr.restore(jax.tree_util.tree_map(lambda x: x, tree),
                               trial)
        for (p, x), (_, y) in zip(
                jax.tree_util.tree_flatten_with_path(tree)[0],
                jax.tree_util.tree_flatten_with_path(restored)[0]):
            assert x.dtype == y.dtype, p
            assert jnp.array_equal(x, y), p
