"""Two-process checkpoint-corruption fallback: the multi-host broadcast
path of the verified-checkpoint story (``_agreed_latest_step``).

The worker (_two_process_corrupt_worker.py) saves two checkpoints on a
shared directory, corrupts the latest on the chief, and asserts BOTH
processes broadcast-agree on the fallback step and restore it — for the
single-file format and for the sharded format with a deleted shard.
"""

import os

import pytest

from _cluster_harness import run_two_process

pytestmark = pytest.mark.slow      # real two-process cluster spawn


def test_corrupt_fallback_agrees_across_processes(tmp_path):
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_two_process_corrupt_worker.py")
    run_two_process(worker, args=(str(tmp_path),), timeout=600)
