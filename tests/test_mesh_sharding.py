"""Mesh construction + sharding-rule tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_example_tpu.parallel.mesh import (
    AxisNames, MeshConfig, batch_axis_size, build_mesh, local_mesh)
from distributed_tensorflow_example_tpu.parallel.sharding import (
    ShardingRules, batch_pspec, shard_batch, shard_params)


def test_default_mesh_all_data(cpu8):
    mesh = build_mesh(devices=cpu8)
    assert mesh.shape[AxisNames.DATA] == 8
    assert batch_axis_size(mesh) == 8
    assert mesh.axis_names == AxisNames.ALL


def test_mesh_wildcard_axis(cpu8):
    mesh = build_mesh({"data": -1, "model": 2}, devices=cpu8)
    assert mesh.shape[AxisNames.DATA] == 4
    assert mesh.shape[AxisNames.MODEL] == 2


def test_mesh_shape_mismatch_raises(cpu8):
    with pytest.raises(ValueError):
        build_mesh({"data": 3}, devices=cpu8)
    with pytest.raises(ValueError):
        build_mesh({"data": -1, "model": -1}, devices=cpu8)


def test_local_mesh_subset():
    mesh = local_mesh(4)
    assert batch_axis_size(mesh) == 4


def test_batch_sharding_splits_leading_dim(cpu8):
    mesh = build_mesh(devices=cpu8)
    batch = {"x": np.zeros((16, 4), np.float32)}
    sharded = shard_batch(mesh, batch)
    # each device holds 16/8 = 2 rows
    shard_shapes = {s.data.shape for s in sharded["x"].addressable_shards}
    assert shard_shapes == {(2, 4)}


def test_sharding_rules_first_match_wins():
    rules = ShardingRules(rules=[
        (r"attn/.*kernel", P(None, "model")),
        (r"kernel", P()),
    ])
    assert rules.spec_for("layer0/attn/q/kernel", (64, 64)) == P(None, "model")
    assert rules.spec_for("layer0/mlp/kernel", (64, 64)) == P()


def test_fsdp_fallback_shards_largest_divisible_dim():
    rules = ShardingRules(fsdp_axis_size=4, fsdp_min_size=16)
    spec = rules.spec_for("fc/kernel", (8, 12))
    assert spec == P(None, AxisNames.FSDP)   # 12 % 4 == 0, largest div dim
    # tiny params stay replicated
    assert rules.spec_for("fc/bias", (10,)) == P()
    # nothing divisible → replicated
    assert rules.spec_for("odd/kernel", (7, 9)) == P()


def test_shard_params_fsdp_layout(cpu8):
    mesh = build_mesh({"fsdp": 8}, devices=cpu8)
    params = {"w": np.ones((16, 32), np.float32),
              "b": np.zeros((32,), np.float32)}
    rules = ShardingRules(fsdp_axis_size=8, fsdp_min_size=64)
    placed = shard_params(mesh, params, rules)
    # w sharded over fsdp on dim 1 (32 is largest and divisible)
    assert {s.data.shape for s in placed["w"].addressable_shards} == {(16, 4)}
    # b replicated
    assert {s.data.shape for s in placed["b"].addressable_shards} == {(32,)}


def test_state_shardings_strict_for_params_relaxed_for_derived(cpu8):
    """A rule-matched PARAM whose dim doesn't divide the axis is a loud
    placement error (silent replication would be a quiet perf/memory
    regression); the same mismatch on a DERIVED opt-state leaf (e.g.
    adafactor's factored vectors) still relaxes to replicated
    (ADVICE r3 #2)."""
    from distributed_tensorflow_example_tpu.parallel.sharding import (
        ShardingRules, state_shardings)
    mesh = local_mesh(8, {"data": 2, "model": 4})
    rules = ShardingRules(rules=[(r"kernel", P(None, "model"))])
    # params: 6 % 4 != 0 -> loud
    bad_state = {"params": {"layer": {"kernel": jnp.zeros((4, 6))}}}
    with pytest.raises(ValueError, match="does not fit param"):
        state_shardings(mesh, bad_state, rules)
    # derived opt-state with the same path fragment -> replicated, no error
    derived = {"opt_state": {"mu": {"layer": {"kernel": jnp.zeros((4, 6))}}}}
    sh = state_shardings(mesh, derived, rules)
    leaf = sh["opt_state"]["mu"]["layer"]["kernel"]
    assert leaf.spec == P()
    # divisible params place normally
    ok_state = {"params": {"layer": {"kernel": jnp.zeros((4, 8))}}}
    sh = state_shardings(mesh, ok_state, rules)
    assert sh["params"]["layer"]["kernel"].spec == P(None, "model")
