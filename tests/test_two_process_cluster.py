"""Two-process CPU cluster integration test (SURVEY.md §4 item 3, §7
hard-part 1): boots a REAL 2-process jax.distributed cluster on localhost
(the analogue of the reference's in-process multi-server fixture,
``tf.test.create_local_cluster``) and asserts that the multi-process code
paths produce exactly the single-process result.

Covered (all unreachable from process_count=1 tests):
- ``jax.distributed.initialize`` via ``runtime.distributed.initialize``
  with worker 0 as coordinator (ClusterSpec-driven)
- ``shard_batch``'s ``make_array_from_process_local_data`` branch
- checkpoint save through ``process_allgather`` of non-addressable
  (cross-process-replicated, fsdp-sharded) arrays + the broadcast
  restore-or-init decision
- SHARDED checkpoint save/restore (fsdp=8 spanning both processes):
  each process writes exactly its own disjoint piece set, the two-phase
  commit barriers, and the selective piece-wise restore reassembles the
  identical state (asserted inside the worker); the host-side test also
  restores that 2-process checkpoint single-process (elastic restart)
- coordination-service ``barrier()``
"""

import os
import sys

import numpy as np
import pytest

from _cluster_harness import run_two_process

# multi-minute on the gate machine: a real two-process jax.distributed
# cluster spawn per test — the tier-1 fast lane (-m "not slow") skips
# these; the full suite remains the pre-ship gate
pytestmark = pytest.mark.slow

_DIR = os.path.dirname(os.path.abspath(__file__))
_WORKER = os.path.join(_DIR, "_two_process_worker.py")


@pytest.fixture(scope="module")
def two_proc_result(tmp_path_factory):
    outdir = str(tmp_path_factory.mktemp("twoproc"))
    run_two_process(_WORKER, [outdir], timeout=300)
    return outdir


def test_two_process_run_completes(two_proc_result):
    for pid in (0, 1):
        assert os.path.exists(os.path.join(two_proc_result,
                                           f"proc{pid}.npz"))


def test_processes_agree_bitwise(two_proc_result):
    """Replicated-state SPMD: both processes must hold identical params
    and identical loss histories."""
    z0 = np.load(os.path.join(two_proc_result, "proc0.npz"))
    z1 = np.load(os.path.join(two_proc_result, "proc1.npz"))
    assert set(z0.files) == set(z1.files)
    for k in z0.files:
        np.testing.assert_array_equal(z0[k], z1[k], err_msg=k)


def test_two_process_equals_single_process(two_proc_result):
    """The SyncReplicas invariant extends across processes: the 2-process
    4+4-device run must match a single-process 8-device run on the same
    global batch sequence (same mesh shape, same seeds, with a mid-run
    checkpoint restore in the 2-proc case that must be a no-op)."""
    import jax

    sys.path.insert(0, _DIR)
    from _two_process_worker import (GLOBAL_BATCH, STEPS_AFTER, STEPS_BEFORE,
                                     dataset)

    from distributed_tensorflow_example_tpu.config import (MeshShape,
                                                           OptimizerConfig)
    from distributed_tensorflow_example_tpu.data.loader import ShardedLoader
    from distributed_tensorflow_example_tpu.models.mlp import MLP
    from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
    from distributed_tensorflow_example_tpu.parallel.sharding import (
        ShardingRules)
    from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
        SyncReplicas)
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)

    mesh = local_mesh(8, {"data": 2, "fsdp": 4})
    model = MLP(in_dim=20, hidden=16, num_classes=4)
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
    sync = SyncReplicas(model.loss, tx, mesh,
                        rules=ShardingRules(fsdp_axis_size=4, fsdp_min_size=1))
    state = sync.init(model.init, seed=0)
    loader = iter(ShardedLoader(dataset(), GLOBAL_BATCH, process_index=0,
                                num_processes=1, shuffle=True, seed=7))
    losses = []
    for _ in range(STEPS_BEFORE + STEPS_AFTER):
        state, m = sync.step(state, sync.shard_batch(next(loader)))
        losses.append(float(jax.device_get(m["loss"])))

    z0 = np.load(os.path.join(two_proc_result, "proc0.npz"))
    np.testing.assert_allclose(z0["losses"], np.asarray(losses),
                               rtol=1e-6, atol=1e-7)
    ref = [np.asarray(p) for p in jax.tree_util.tree_leaves(
        jax.device_get(state.params))]
    for i, want in enumerate(ref):
        np.testing.assert_allclose(z0[f"p{i}"], want, rtol=1e-6, atol=1e-7,
                                   err_msg=f"param leaf {i}")


def test_sharded_ckpt_restores_across_process_counts(two_proc_result):
    """Elasticity: a checkpoint written by TWO processes (one shard file
    each) restores in ONE process onto the local 8-device mesh — the
    slice-restart story where the new job shape need not match the old."""
    import glob

    import jax

    from distributed_tensorflow_example_tpu.ckpt.checkpoint import (
        CheckpointManager)
    from distributed_tensorflow_example_tpu.config import OptimizerConfig
    from distributed_tensorflow_example_tpu.models.mlp import MLP
    from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
    from distributed_tensorflow_example_tpu.parallel.sharding import (
        ShardingRules)
    from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
        SyncReplicas)
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)

    sh_dir = os.path.join(two_proc_result, "ckpt_sharded")
    assert len(glob.glob(os.path.join(sh_dir, "*.shard-*-of-2.npz"))) == 2

    mesh = local_mesh(8, {"fsdp": 8})
    model = MLP(in_dim=24, hidden=32, num_classes=4)
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
    sync = SyncReplicas(model.loss, tx, mesh,
                        rules=ShardingRules(fsdp_axis_size=8,
                                            fsdp_min_size=1))
    # the worker saved a fresh seed=3 init: the same seeded init here is
    # the bit-exact expectation. The TEMPLATE deliberately uses another
    # seed so template values passing through unchanged would fail.
    expected = sync.init(model.init, seed=3)
    template = sync.init(model.init, seed=99)
    restored = CheckpointManager(sh_dir).restore(template)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(expected)[0],
            jax.tree_util.tree_flatten_with_path(restored)[0]):
        if jax.dtypes.issubdtype(getattr(a, "dtype", np.float32),
                                 jax.dtypes.prng_key):
            assert np.array_equal(jax.random.key_data(a),
                                  jax.random.key_data(b)), path
        else:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=str(path))
