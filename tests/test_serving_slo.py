"""SLO-aware overload resilience (round 18): chunked prefill,
priority admission, graceful shedding.

- estimator-split unit tests: the decode-step EMA must be immune to
  prefill-chunk observations (the satellite fix — Retry-After stays a
  decode measurement under chunked prefill), and ``time_for`` prices
  each work class by its own component;
- pure-function tests for the ordered admission queue
  (:func:`~.serving_batch.select_index`: class order, EDF within
  class, FIFO ties, aging) including the deterministic injected-clock
  NO-STARVATION bound — a sustained interactive stream can delay a
  queued best_effort request only until aging promotes it;
- the pressure ladder's hysteresis
  (:func:`~.serving_batch.compute_pressure_level`);
- engine-level chunked-prefill byte parity (chunking on vs off vs the
  monolithic oracle) including the prefix-cache-hit, weight-int8 and
  speculation compositions, the kv-int8 drift-gate composition, and
  the ``prefill_chunk_tokens=0`` bitwise no-op (identical dispatch
  counters);
- brownout shedding by class (batch AND best_effort rungs), the
  immediate feasibility shed (429-class ShedError, never a 504 after
  wasted queue time), and the /healthz saturation fields;
- the router-side satellite: a probe answering 200 with
  ``saturated: true`` demotes an overloaded-but-live replica to
  ``degraded`` (it stops taking admissions) and the next unsaturated
  probe re-admits it.
"""

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "experiments"))

import serving_load  # noqa: E402

from distributed_tensorflow_example_tpu.serving import (  # noqa: E402
    load_stepwise)
from distributed_tensorflow_example_tpu.serving_batch import (  # noqa: E402
    PRESSURE_STATES, PRIORITIES, GenerationEngine, GenRequest,
    RetryAfterEstimator, ShedError, compute_pressure_level,
    select_index)
from distributed_tensorflow_example_tpu.serving_router import (  # noqa: E402
    ReplicaRouter)

PROMPT_LEN = 12
MAX_NEW = 8
SLOTS = 3
BLOCK = 4


@pytest.fixture(scope="module")
def chunk_dir(tmp_path_factory):
    """ONE paged export carrying the chunked-prefill program, shared
    by the engine-level tests (the shared-export pattern)."""
    d = str(tmp_path_factory.mktemp("slo"))
    vocab = serving_load.build_export(
        d, prompt_len=PROMPT_LEN, max_new=MAX_NEW, slots=SLOTS,
        seed=0, paged=True, block_size=BLOCK, prefill_chunk=BLOCK)
    return d, vocab


def _prompts(vocab, n, seed=0, lo=1, hi=PROMPT_LEN):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, vocab, (int(rs.randint(lo, hi + 1)),))
            .astype(np.int32) for _ in range(n)]


def _run_engine(d, prompts, *, max_new=6, chunk=0, **kw):
    eng = GenerationEngine(load_stepwise(d),
                           prefill_chunk_tokens=chunk, **kw).start()
    try:
        handles = [eng.submit(p, max_new=max_new) for p in prompts]
        outs = [h.result(timeout=120) for h in handles]
        return outs, eng.stats()
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# satellite: the split Retry-After estimator
# ---------------------------------------------------------------------------

def test_estimator_decode_ema_immune_to_prefill_chunks():
    """The satellite fix pinned as math: chunk observations move ONLY
    the prefill EMA — the decode-step EMA (and with it estimate(), the
    queue-full Retry-After) is bitwise unchanged by any amount of
    chunk work sharing the iteration."""
    a, b = RetryAfterEstimator(alpha=0.5), RetryAfterEstimator(alpha=0.5)
    for e in (a, b):
        e.observe(0.010)
        e.observe(0.020)
    # b additionally sees heavy chunk traffic
    for _ in range(50):
        b.observe_prefill(0.500)
    assert b.ema_step_s == a.ema_step_s
    assert b.estimate(4.0, queue_ahead=3, slots=2) \
        == a.estimate(4.0, queue_ahead=3, slots=2)
    assert b.ema_prefill_chunk_s == pytest.approx(0.5, rel=1e-6)
    assert a.ema_prefill_chunk_s is None


def test_estimator_time_for_prices_both_components():
    est = RetryAfterEstimator(alpha=1.0)
    assert est.time_for(10) is None          # no decode signal yet
    est.observe(0.010)
    # no chunk signal: chunks priced at the decode EMA fallback
    assert est.time_for(10) == pytest.approx(0.10)
    assert est.time_for(10, prefill_chunks=2) == pytest.approx(0.12)
    est.observe_prefill(0.100)
    assert est.time_for(10, prefill_chunks=2) == pytest.approx(0.30)
    # the tokens-per-dispatch EMA still converts row-steps (spec)
    est.observe_advance(2.0)
    assert est.time_for(10) == pytest.approx(0.010 * 10 / 2.0)


def test_estimator_ema_step_alpha_unchanged():
    """The pre-split observe() arithmetic is untouched (regression
    guard for the PR-10 estimator tests' contract)."""
    est = RetryAfterEstimator(alpha=0.2)
    est.observe(1.0)
    est.observe(2.0)
    assert est.ema_step_s == pytest.approx(1.0 + 0.2 * 1.0)
    assert est.seeded


# ---------------------------------------------------------------------------
# ordered admission: select_index
# ---------------------------------------------------------------------------

def _req(priority="interactive", submitted_at=0.0, deadline_t=0.0):
    r = GenRequest(prompt=np.array([1], np.int32), max_new=4,
                   temperature=0.0, top_k=0, top_p=0.0, seed=0,
                   eos_id=None, pad_id=0)
    r.priority = priority
    r.submitted_at = submitted_at
    r.deadline_t = deadline_t
    return r


def test_select_index_is_fifo_for_priorityless_traffic():
    q = [_req(submitted_at=i) for i in range(5)]
    assert select_index(q, now=100.0, aging_s=2.0) == 0


def test_select_index_class_order_and_edf_within_class():
    q = [_req("best_effort"), _req("batch"),
         _req("interactive", deadline_t=50.0),
         _req("interactive", deadline_t=20.0),
         _req("interactive")]
    # best class first; earliest deadline first inside it; a request
    # with no deadline sorts after any deadline-carrying sibling
    assert select_index(q, now=0.0, aging_s=0.0) == 3
    del q[3]
    assert select_index(q, now=0.0, aging_s=0.0) == 2
    del q[2]
    assert select_index(q, now=0.0, aging_s=0.0) == 2   # bare interactive
    del q[2]
    assert select_index(q, now=0.0, aging_s=0.0) == 1   # batch over b_e


def test_select_index_aging_promotes_one_class_per_period():
    be = _req("best_effort", submitted_at=0.0)
    inter = _req("interactive", submitted_at=3.9)
    q = [be, inter]
    # waited 2 aging periods: best_effort reaches rank 0 and wins on
    # queue order against the younger interactive
    assert select_index(q, now=4.0, aging_s=2.0) == 0
    # only one period waited: still behind interactive
    assert select_index(q, now=2.5, aging_s=2.0) == 1
    # aging disabled: interactive always wins
    assert select_index(q, now=1e9, aging_s=0.0) == 1


def test_no_starvation_for_deadline_less_behind_edf_stream():
    """Aging is unbounded below zero, so EDF within a class cannot
    starve a deadline-less sibling: an aged request eventually
    outranks every deadline-carrying newcomer outright."""
    aging_s = 1.0
    plain = _req("interactive", submitted_at=0.0)
    queue = [plain]
    now, served_at = 0.0, None
    for step in range(100):
        # fresh deadline-carrying interactive arrivals, forever —
        # each would beat `plain` under pure EDF
        queue.append(_req("interactive", submitted_at=now,
                          deadline_t=now + 0.5))
        i = select_index(queue, now, aging_s=aging_s)
        if queue[i] is plain:
            served_at = now
            break
        del queue[i]
        now += 0.1
    assert served_at is not None, "deadline-less request starved"
    assert served_at <= 2 * aging_s


def test_no_starvation_under_sustained_interactive_stream():
    """The satellite bound, deterministic with an injected clock and
    no engine: a best_effort request queued at t=0 behind an endless
    interactive arrival stream MUST be selected within rank *
    aging_s (here 2 classes * 1s) — aging makes starvation
    impossible by construction."""
    aging_s = 1.0
    be = _req("best_effort", submitted_at=0.0)
    queue = [be]
    now = 0.0
    served_be_at = None
    for step in range(100):
        # one fresh interactive arrival every 100 ms, forever
        queue.append(_req("interactive", submitted_at=now))
        i = select_index(queue, now, aging_s=aging_s)
        if queue[i] is be:
            served_be_at = now
            break
        del queue[i]
        now += 0.1
    assert served_be_at is not None, "best_effort starved"
    assert served_be_at <= len(PRIORITIES) * aging_s


# ---------------------------------------------------------------------------
# the pressure ladder
# ---------------------------------------------------------------------------

def test_pressure_ladder_levels_and_hysteresis():
    assert compute_pressure_level(0, 0.0) == 0
    assert compute_pressure_level(0, 0.49) == 0
    assert compute_pressure_level(0, 0.50) == 1
    assert compute_pressure_level(0, 0.75) == 2
    assert compute_pressure_level(0, 0.95) == 3
    # exit needs the score to fall BELOW enter - hysteresis: a score
    # oscillating on the boundary cannot flap the state
    assert compute_pressure_level(2, 0.70) == 2
    assert compute_pressure_level(2, 0.64) == 1
    assert compute_pressure_level(3, 0.82) == 3
    assert compute_pressure_level(3, 0.30) == 0
    assert len(PRESSURE_STATES) == 4


# ---------------------------------------------------------------------------
# chunked prefill: engine-level parity + compositions
# ---------------------------------------------------------------------------

def test_chunked_prefill_byte_parity_and_knob_noop(chunk_dir):
    """Greedy bytes byte-identical chunking on vs off over mixed
    prompt lengths, and the 0-knob is a bitwise no-op: identical
    dispatch counters (no chunk program ever dispatches)."""
    d, vocab = chunk_dir
    prompts = _prompts(vocab, 6, seed=1)
    off, s_off = _run_engine(d, prompts, chunk=0)
    on, s_on = _run_engine(d, prompts, chunk=BLOCK)
    assert on == off
    assert s_off["prefill_chunks"] == 0
    assert s_off["prefills"] == len(prompts)
    assert s_on["prefills"] == 0
    want = sum(-(-int(p.size) // BLOCK) for p in prompts)
    assert s_on["prefill_chunks"] == want
    # identical tokens out; decode DISPATCH counts may differ (the
    # whole point: neighbors keep stepping while a prompt chunks, so
    # sharing patterns shift) — per-request bytes cannot
    assert s_on["tokens_out"] == s_off["tokens_out"]


def test_chunked_prefill_budget_below_exported_width(chunk_dir):
    """A smaller block-multiple budget than the exported chunk width
    dispatches MORE, smaller chunks — bytes unchanged."""
    d, vocab = chunk_dir
    # export width is BLOCK, so equal here; assert the validation
    # rejects a non-multiple and an over-wide budget loudly instead
    with pytest.raises(ValueError, match="multiple of block_size"):
        GenerationEngine(load_stepwise(d),
                         prefill_chunk_tokens=BLOCK + 1)
    with pytest.raises(ValueError, match="exceeds this artifact"):
        GenerationEngine(load_stepwise(d),
                         prefill_chunk_tokens=4 * BLOCK)


def test_chunked_prefill_composes_with_prefix_cache(chunk_dir):
    """A chunk-prefilled cold prompt enters the prefix cache; the
    identical repeat mounts it with ZERO additional chunk dispatches
    and byte-identical output; a divergent-suffix prompt reuses the
    cached leading blocks."""
    d, vocab = chunk_dir
    rs = np.random.RandomState(7)
    base = rs.randint(0, vocab, (PROMPT_LEN,)).astype(np.int32)
    eng = GenerationEngine(load_stepwise(d),
                           prefill_chunk_tokens=BLOCK).start()
    try:
        a = eng.submit(base, max_new=6).result(timeout=120)
        chunks0 = eng.stats()["prefill_chunks"]
        b = eng.submit(base, max_new=6).result(timeout=120)
        st = eng.stats()
        assert b == a
        assert st["prefill_chunks"] == chunks0
        assert st["prefix_cache_hits"] == 1
        assert st["prefill_tokens_saved"] > 0
    finally:
        eng.close()
    # the chunk-written block BYTES equal the monolithic prefill's:
    # an engine WITHOUT chunking must produce the same continuation
    # from its own cold prefill of the same prompt
    ref, _ = _run_engine(d, [base], chunk=0)
    assert a == ref[0]


def test_chunked_prefill_composes_with_speculation(tmp_path):
    """spec_tokens > 0 + chunked prefill: byte parity chunking on vs
    off on the repetitive workload, with drafts genuinely accepted."""
    d = str(tmp_path / "spec_chunk")
    vocab = serving_load.build_export(
        d, prompt_len=PROMPT_LEN, max_new=12, slots=SLOTS, seed=0,
        paged=True, block_size=BLOCK, prefill_chunk=BLOCK,
        spec_tokens=4)
    rs = np.random.RandomState(3)
    pattern = rs.randint(0, vocab, (3,)).astype(np.int32)
    prompts = [np.tile(pattern, 4)[:n].astype(np.int32)
               for n in (12, 7, 9)]
    off, s_off = _run_engine(d, prompts, max_new=12, chunk=0,
                             spec_tokens=4)
    on, s_on = _run_engine(d, prompts, max_new=12, chunk=BLOCK,
                           spec_tokens=4)
    assert on == off
    assert s_on["prefill_chunks"] > 0
    assert s_on["spec_accepted"] > 0
    assert s_on["spec_accepted"] == s_off["spec_accepted"]


def test_chunked_prefill_composes_with_weight_int8(tmp_path):
    """weight_quant='int8' bakes int8 into the DECODE programs only —
    prefill (and the chunk program) stays full precision, so chunking
    on vs off stays byte-identical even on the quantized export."""
    d = str(tmp_path / "w8_chunk")
    vocab = serving_load.build_export(
        d, prompt_len=PROMPT_LEN, max_new=MAX_NEW, slots=SLOTS,
        seed=0, paged=True, block_size=BLOCK, prefill_chunk=BLOCK,
        weight_quant="int8")
    prompts = _prompts(vocab, 4, seed=5)
    off, _ = _run_engine(d, prompts, chunk=0)
    on, s_on = _run_engine(d, prompts, chunk=BLOCK)
    assert on == off
    assert s_on["prefill_chunks"] > 0


def test_chunked_prefill_kv_int8_rides_drift_gate(tmp_path):
    """The kv-int8 composition: a chunk re-reads PRIOR chunks through
    the quantize/dequant pair the monolithic prefill never pays, so
    byte identity is not the contract — the repo's documented
    token-agreement drift bound is (DESIGN.md §15)."""
    d = str(tmp_path / "kv8_chunk")
    vocab = serving_load.build_export(
        d, prompt_len=PROMPT_LEN, max_new=MAX_NEW, slots=SLOTS,
        seed=0, paged=True, block_size=BLOCK, prefill_chunk=BLOCK,
        weight_quant="int8", kv_cache_dtype="int8")
    prompts = _prompts(vocab, 4, seed=9)
    off, _ = _run_engine(d, prompts, chunk=0)
    on, s_on = _run_engine(d, prompts, chunk=BLOCK)
    assert s_on["prefill_chunks"] > 0
    agreement = serving_load.token_agreement([on], [off])
    assert agreement >= serving_load.INT8_MIN_AGREEMENT


def test_chunked_prefill_respects_deadline_and_cancel(chunk_dir):
    """A mid-chunked-prefill slot is cancellable and deadline-bound
    like any live slot: its blocks return and neighbors keep going."""
    from distributed_tensorflow_example_tpu.serving_batch import \
        RequestCancelledError
    d, vocab = chunk_dir
    rs = np.random.RandomState(11)
    long_p = rs.randint(0, vocab, (PROMPT_LEN,)).astype(np.int32)
    eng = GenerationEngine(load_stepwise(d), prefix_cache=False,
                           prefill_chunk_tokens=BLOCK).start()
    try:
        free0 = eng.stats()["blocks_free"]
        h = eng.submit(long_p, max_new=MAX_NEW)
        h.cancel()
        with pytest.raises(RequestCancelledError):
            h.result(timeout=120)
        t0 = time.monotonic()
        while eng.stats()["blocks_free"] != free0 \
                and time.monotonic() - t0 < 30:
            time.sleep(0.005)
        assert eng.stats()["blocks_free"] == free0
        # the engine still serves to parity afterwards
        out = eng.submit(long_p, max_new=4).result(timeout=120)
        ref, _ = _run_engine(d, [long_p], max_new=4, chunk=0)
        assert out == ref[0]
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# shedding: ladder by class, feasibility, healthz fields
# ---------------------------------------------------------------------------

def _wait(pred, timeout=30.0, what="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.002)
    raise AssertionError(f"timed out waiting for {what}")


def test_brownout_sheds_batch_and_best_effort_not_interactive(
        chunk_dir):
    """The admission-time enforcement point, rung by rung — driven on
    an UNSTARTED engine with the ladder position pinned directly, so
    no scheduler-drain race can move the rung mid-assertion (the
    started-engine integration of the same ladder is the tier-1
    overload_storm chaos scenario)."""
    d, vocab = chunk_dir
    prompts = _prompts(vocab, 4, seed=13)
    eng = GenerationEngine(load_stepwise(d), max_queue=16)
    try:
        eng._pressure_level = 1          # shed_best_effort
        with pytest.raises(ShedError) as ei:
            eng.submit(prompts[0], max_new=2, priority="best_effort")
        assert ei.value.retry_after >= 0.0
        assert "pressure" in str(ei.value)
        eng.submit(prompts[0], max_new=2, priority="batch")
        eng._pressure_level = 2          # shed_batch
        with pytest.raises(ShedError):
            eng.submit(prompts[1], max_new=2, priority="batch")
        with pytest.raises(ShedError):
            eng.submit(prompts[1], max_new=2,
                       priority="best_effort")
        eng.submit(prompts[1], max_new=2)        # interactive admits
        eng._pressure_level = 3          # interactive_only
        with pytest.raises(ShedError):
            eng.submit(prompts[2], max_new=2, priority="batch")
        eng.submit(prompts[2], max_new=2)        # still admits
        st = eng.stats()
        assert st["shed_batch"] == 2
        assert st["shed_best_effort"] == 2
        assert st["shed_interactive"] == 0
        assert st["shed"] == 4
    finally:
        eng.close()


def test_brownout_level3_sheds_queued_non_interactive(chunk_dir):
    """interactive_only additionally sweeps QUEUED batch/best_effort
    requests: pre-loaded on an unstarted engine with the ladder
    pinned high via a wedged score (tiny max_queue), the scheduler's
    first pressure tick must shed them 429-class while the
    interactive backlog is served to completion."""
    d, vocab = chunk_dir
    prompts = _prompts(vocab, 6, seed=31)
    eng = GenerationEngine(load_stepwise(d), max_queue=4)
    try:
        # pre-load: 3 interactive + 1 batch — depth 4/4 = score 1.0,
        # so the FIRST scheduler tick enters interactive_only and
        # sweeps the queued batch request before any admission
        inter = [eng.submit(p, max_new=2) for p in prompts[:3]]
        victim = eng.submit(prompts[3], max_new=2, priority="batch")
        eng.start()
        with pytest.raises(ShedError):
            victim.result(timeout=120)
        outs = [h.result(timeout=120) for h in inter]
        assert all(outs)
        st = eng.stats()
        assert st["shed_batch"] == 1
        assert st["shed_interactive"] == 0
        _wait(lambda: eng.stats()["pressure"] == "healthy",
              what="recovery to healthy")
        assert eng.stats()["pressure_transitions"] >= 2
    finally:
        eng.close()


def test_shed_policy_off_disables_ladder_and_feasibility(chunk_dir):
    d, vocab = chunk_dir
    prompts = _prompts(vocab, 8, seed=17)
    eng = GenerationEngine(load_stepwise(d), max_queue=16,
                           shed_policy="off").start()
    try:
        handles = [eng.submit(p, max_new=MAX_NEW) for p in prompts]
        # deep backlog, but the ladder is off: best_effort admits fine
        h = eng.submit(prompts[0], max_new=2, priority="best_effort")
        assert h.result(timeout=120)
        [x.result(timeout=120) for x in handles]
        st = eng.stats()
        assert st["shed"] == 0
        assert st["pressure"] == "healthy"
        assert st["pressure_transitions"] == 0
    finally:
        eng.close()


def test_infeasible_deadline_shed_immediately_as_429_class(chunk_dir):
    """A queued request whose deadline cannot be met at the MEASURED
    rate is shed NOW (ShedError -> HTTP 429 + Retry-After), instead of
    rotting in the queue and 504ing — and it never takes a slot."""
    d, vocab = chunk_dir
    prompts = _prompts(vocab, 3, seed=19)
    eng = GenerationEngine(load_stepwise(d))
    # pre-seed the measured rate BEFORE start (the test's injected
    # "measured" signal: 10 s per decode step makes ANY bounded
    # deadline infeasible deterministically — no sleeps, no races)
    eng._retry.observe(10.0)
    victim = eng.submit(prompts[1], max_new=MAX_NEW,
                        deadline_ms=5_000)
    survivor = eng.submit(prompts[2], max_new=2)
    eng.start()
    try:
        with pytest.raises(ShedError) as ei:
            victim.result(timeout=120)
        assert "deadline infeasible" in str(ei.value)
        assert survivor.result(timeout=120)
        st = eng.stats()
        assert st["shed_infeasible"] == 1
        assert st["shed_interactive"] == 1
        assert st["shed"] == 1
        # the whole point: a 429-class shed, not a 504 after rotting
        assert st["deadline_expired"] == 0
    finally:
        eng.close()


def test_healthz_carries_saturation_fields(chunk_dir):
    d, vocab = chunk_dir
    eng = GenerationEngine(load_stepwise(d)).start()
    try:
        h = eng.health()
        assert h["pressure"] == "healthy"
        assert h["saturated"] is False
        assert h["queue_age_s"] == 0.0
        assert h["queue_limit"] == 64
        # a queued request ages visibly
        handles = [eng.submit(p, max_new=MAX_NEW)
                   for p in _prompts(vocab, SLOTS + 3, seed=29)]
        _wait(lambda: eng.health()["queue_age_s"] > 0.0,
              what="queue age becoming visible")
        [x.result(timeout=120) for x in handles]
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# router satellite: saturated replicas demote to degraded
# ---------------------------------------------------------------------------

class _FakeReplica:
    """A minimal /healthz endpoint whose saturation answer the test
    flips — the router probe test's stand-in for an overloaded-but-
    live engine."""

    def __init__(self):
        self.saturated = False
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = json.dumps({
                    "status": "live", "draining": False,
                    "queue_age_s": 9.9 if fake.saturated else 0.0,
                    "pressure": ("shed_batch" if fake.saturated
                                 else "healthy"),
                    "saturated": fake.saturated,
                    "mono_now": time.perf_counter()}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_router_demotes_saturated_replica_to_last_resort():
    """A live-but-saturated replica stops being PREFERRED (a healthy
    sibling takes its traffic) but remains the last-resort tier — a
    fleet-wide brownout must reach the replicas' own class ladders,
    never collapse into a blanket router 503 for the interactive
    traffic those ladders protect."""
    fake, healthy = _FakeReplica(), _FakeReplica()
    router = ReplicaRouter([f"http://127.0.0.1:{fake.port}",
                            f"http://127.0.0.1:{healthy.port}"],
                           probe_interval_s=0.02)
    sat_name = f"127.0.0.1:{fake.port}"
    try:
        router.start()
        _wait(lambda: set(router.replica_states().values())
              == {"healthy"}, what="both replicas healthy")
        fake.saturated = True
        _wait(lambda: router.replica_states()[sat_name]
              == "saturated", what="saturation demotion")
        # with a healthy sibling, the saturated replica is never picked
        for _ in range(5):
            assert router._pick(set(), None).name != sat_name
        assert router.fleet_health()["status"] == "live"
        # the healthy sibling gone: the saturated replica is the last
        # resort — still routed to, fleet healthz says saturated (503
        # pushback upstream) rather than unserved
        _wait(lambda: router.replica_states()[sat_name]
              == "saturated", what="state settle")
        picked = router._pick({f"127.0.0.1:{healthy.port}"}, None)
        assert picked is not None and picked.name == sat_name
        healthy.saturated = True
        _wait(lambda: set(router.replica_states().values())
              == {"saturated"}, what="fleet-wide saturation")
        assert router._pick(set(), None) is not None
        assert router.fleet_health()["status"] == "saturated"
        # recovery: the next unsaturated 200 probe restores healthy
        fake.saturated = healthy.saturated = False
        _wait(lambda: set(router.replica_states().values())
              == {"healthy"}, what="re-admission after recovery")
        assert router._pick(set(), None) is not None
    finally:
        router.close()
        fake.close()
        healthy.close()


# ---------------------------------------------------------------------------
# HTTP surface: priority knob + chunk knob auto-off
# ---------------------------------------------------------------------------

def test_http_priority_knob_and_defaults(chunk_dir):
    import urllib.error
    import urllib.request

    from distributed_tensorflow_example_tpu.serving_http import \
        PredictServer
    d, vocab = chunk_dir

    def post(port, name, payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/models/{name}:generate",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    with PredictServer(d, default_priority="batch",
                       prefill_chunk_tokens=BLOCK) as srv:
        out = post(srv.port, srv.name,
                   {"inputs": {"input_ids": [[1, 2, 3]]},
                    "max_new": 3, "priority": "interactive"})
        assert len(out["generations"][0]) == 3
        # default class applies when the payload carries none
        out = post(srv.port, srv.name,
                   {"inputs": {"input_ids": [[4, 5]]}, "max_new": 2})
        assert len(out["generations"][0]) == 2
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(srv.port, srv.name,
                 {"inputs": {"input_ids": [[1]]}, "priority": "vip"})
        assert ei.value.code == 400
        assert "priority" in json.loads(ei.value.read())["error"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(srv.port, srv.name,
                 {"inputs": {"input_ids": [[1]]}, "priority": 3})
        assert ei.value.code == 400
        # chunking served this traffic (the knob reached the engine)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/stats") as r:
            st = json.loads(r.read())["generate"]
        assert st["prefill_chunk_tokens"] == BLOCK
        assert st["prefill_chunks"] > 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz") as r:
            h = json.loads(r.read())
        assert h["pressure"] == "healthy" and h["saturated"] is False


def test_http_chunk_knob_auto_off_without_program(tmp_path):
    """--prefill_chunk_tokens over an artifact without the chunk
    program serves WITHOUT chunking (logged warning), mirroring the
    --spec_tokens auto-off contract."""
    from distributed_tensorflow_example_tpu.serving_http import \
        PredictServer
    d = str(tmp_path / "nochunk")
    serving_load.build_export(d, prompt_len=PROMPT_LEN,
                              max_new=MAX_NEW, slots=2, seed=0,
                              paged=True, block_size=BLOCK)
    with PredictServer(d, prefill_chunk_tokens=BLOCK) as srv:
        assert srv.engine.prefill_chunk_tokens == 0
