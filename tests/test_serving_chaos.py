"""Self-healing serving (round 14): chaos-soak fast smoke + targeted
regressions.

- the tier-1 smoke runs ALL seven seeded scenarios from
  experiments/serving_chaos.py against one shared export (the full CLI
  soak is the slow-lane twin);
- regression tests pin the satellite contracts individually: the
  EngineHandle timeout leak (a timed-out wait must cancel and return
  blocks, not keep decoding to max_new), close() raising
  EngineStalledError instead of silently leaking a hung scheduler
  thread (engine AND micro-batcher), queue-full 429/Retry-After parity
  between :predict and :generate, fault-seam inertness (an armed-but-
  never-firing registry is byte- and dispatch-identical to none), and
  the HTTP failure surface (504 deadline, /cancel 200/404/409,
  /healthz, 503 + Retry-After while draining, the http.read seam).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "experiments"))

import serving_chaos  # noqa: E402

from distributed_tensorflow_example_tpu.runtime import faults  # noqa: E402
from distributed_tensorflow_example_tpu.serving import (  # noqa: E402
    load_servable, load_stepwise)
from distributed_tensorflow_example_tpu.serving_batch import (  # noqa: E402
    DeadlineExceededError, EngineStalledError, GenerationEngine,
    MicroBatcher, QueueFullError, RequestCancelledError)
from distributed_tensorflow_example_tpu.serving_http import (  # noqa: E402
    PredictServer)


@pytest.fixture(scope="module")
def chaos_dir(tmp_path_factory):
    """ONE ample-pool paged export shared by the smoke and the
    regressions (the scenarios' shapes live in serving_chaos)."""
    d = str(tmp_path_factory.mktemp("chaos"))
    vocab = serving_chaos.build_chaos_export(d, seed=0)
    return d, vocab


@pytest.fixture(scope="module")
def tight_dir(tmp_path_factory):
    """The deliberately under-provisioned pool for the exhaustion
    scenario."""
    d = str(tmp_path_factory.mktemp("chaos_tight"))
    vocab = serving_chaos.build_chaos_export(
        d, seed=0, num_blocks=serving_chaos.tight_pool_blocks())
    return d, vocab


def _engine(d, **kw):
    kw.setdefault("prefix_cache", False)
    return GenerationEngine(load_stepwise(d), **kw).start()


def _assert_ok(results):
    bad = [r for r in results if not r["ok"]]
    assert not bad, f"chaos scenario(s) failed: {bad}"


def _wait(pred, timeout=30.0, what="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


def _post(port, name, payload, request_id=None, verb="generate"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/{name}:{verb}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json",
                 **({"X-Request-Id": request_id} if request_id
                    else {})})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def _get(port, path):
    """(status, body) without raising on 4xx/5xx."""
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post_raw(port, path, data=b""):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=data)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# ---------------------------------------------------------------------------
# the chaos smoke: all seven scenarios, shared export
# ---------------------------------------------------------------------------

def test_chaos_smoke_failure_injection(chaos_dir):
    """deadline storm / poison step / transient flaky dispatch: the
    quarantine + deadline invariants named in the round-14 acceptance
    criteria (expired requests return blocks exactly; a poisoned step
    fails exactly one request with survivors to byte parity)."""
    d, vocab = chaos_dir
    _assert_ok(serving_chaos.run_scenarios(
        ["deadline_storm", "poison_step", "flaky_dispatch"],
        seed=0, export_dir=d, vocab=vocab))


def test_chaos_smoke_lifecycle(chaos_dir):
    """drain-under-load parity (zero dropped requests), the watchdog
    trip, and the queue-full client retry loop."""
    d, vocab = chaos_dir
    _assert_ok(serving_chaos.run_scenarios(
        ["drain_under_load", "watchdog_trip", "queue_full_retry"],
        seed=0, export_dir=d, vocab=vocab))


def test_chaos_smoke_blocks_exhausted_cancel(tight_dir):
    """Mid-decode exhaustion + live cancellation: blocks come back
    IMMEDIATELY on cancel, the pool recovers to the exact free count,
    and the engine still serves after."""
    d, vocab = tight_dir
    _assert_ok(serving_chaos.run_scenarios(
        ["blocks_cancel"], seed=0, tight_dir=d, vocab=vocab))


def test_chaos_smoke_spec_verify_fault(chaos_dir):
    """Round-16: the decode-step fault seam firing DURING a K-token
    speculative verify dispatch must quarantine/re-dispatch per the
    PR-10 protocol — transient healed to byte parity with one extra
    dispatch, repeat failure evicting exactly the newest admission
    with survivors byte-identical and per-row pos rewound exactly
    (exact blocks_free recovery)."""
    d, vocab = chaos_dir
    _assert_ok(serving_chaos.run_scenarios(
        ["spec_verify_fault"], seed=0, export_dir=d, vocab=vocab))


def test_chaos_smoke_overload_and_long_prompts(chaos_dir):
    """Round-18: the overload storm (interactive protected to byte
    parity at 2x load, best_effort shed 429-class with measured
    Retry-After, exact shed accounting, pressure recovers) and the
    long-prompt storm (chunked prefill interleaves shared decode steps
    between one prompt's chunks, bytes identical to the chunk-off
    engine, exact chunk accounting)."""
    d, vocab = chaos_dir
    _assert_ok(serving_chaos.run_scenarios(
        ["overload_storm", "long_prompt_storm"],
        seed=0, export_dir=d, vocab=vocab))


@pytest.mark.slow
def test_chaos_soak_cli_all_scenarios():
    """The full soak through the CLI entry (fresh process — the
    slow-lane gate)."""
    script = os.path.join(ROOT, "experiments", "serving_chaos.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, script, "--scenario", "all"],
                          capture_output=True, text=True, env=env,
                          timeout=1200)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [json.loads(l) for l in proc.stdout.splitlines() if l]
    summary = lines[-1]
    assert summary["failed"] == 0 and summary["scenarios"] == 8, lines


# ---------------------------------------------------------------------------
# satellite regressions: the handle leak
# ---------------------------------------------------------------------------

def test_handle_timeout_cancels_and_frees_blocks(chaos_dir):
    """The round-9 leak: EngineHandle.result(timeout) must CANCEL on
    timeout — slot retired, blocks back (exact), decoding stopped —
    instead of abandoning a request that runs to max_new."""
    d, vocab = chaos_dir
    eng = _engine(d)
    try:
        free0 = eng.stats()["blocks_free"]
        prompt = (np.arange(1, 8) % vocab).astype(np.int32)
        h = eng.submit(prompt, max_new=16)
        with pytest.raises(TimeoutError, match="cancelled"):
            h.result(timeout=0.02)
        with pytest.raises(RequestCancelledError):
            h.req.future.result(timeout=30)
        _wait(lambda: eng.stats()["blocks_free"] == free0,
              what="cancelled request's blocks returning")
        s = eng.stats()
        assert s["live_slots"] == 0 and s["cancelled"] == 1, s
        # decoding actually STOPPED (the leak kept burning dispatches)
        steps = eng.stats()["decode_steps"]
        time.sleep(0.15)
        assert eng.stats()["decode_steps"] == steps
        # the slot is reallocatable: the engine still serves
        assert len(eng.generate(prompt, timeout=120, max_new=2)) == 2
    finally:
        eng.close()


def test_default_deadline_ms_applies_engine_wide(chaos_dir):
    d, _ = chaos_dir
    eng = _engine(d, default_deadline_ms=1)
    try:
        with pytest.raises(DeadlineExceededError, match="deadline"):
            eng.submit(np.array([1, 2, 3], np.int32),
                       max_new=8).result(timeout=60)
        assert eng.stats()["deadline_expired"] == 1
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# satellite regressions: close() must not lie about a hung thread
# ---------------------------------------------------------------------------

def test_engine_close_raises_stalled_on_hung_scheduler(chaos_dir):
    d, _ = chaos_dir
    eng = _engine(d)
    wedged, release = threading.Event(), threading.Event()
    orig = eng.sw.decode

    def wedge(feats):
        wedged.set()
        release.wait(timeout=60)
        return orig(feats)

    eng.sw.decode = wedge
    try:
        eng.submit(np.array([1, 2, 3], np.int32), max_new=4)
        assert wedged.wait(timeout=30)
        with pytest.raises(EngineStalledError, match="heartbeat"):
            eng.close(timeout=0.2)
    finally:
        release.set()
        eng.close(timeout=30)            # parks clean once released
    assert eng.health()["status"] == "dead"


def test_microbatcher_close_raises_stalled_when_wedged(tmp_path):
    """Same contract for the :predict batcher thread."""
    import jax

    from distributed_tensorflow_example_tpu.config import TrainConfig
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.serving import (
        export_model, serving_signature)
    d = str(tmp_path / "predict")
    m = get_model("mlp", TrainConfig(model="mlp"))
    out = m.init(jax.random.key(0))
    params, extras = out if isinstance(out, tuple) else (out, {})
    export_model(m, params, extras, d, platforms=("cpu",))
    feats = serving_signature(m.dummy_batch(4))
    mb = MicroBatcher(load_servable(d), batch_max_size=4,
                      batch_max_wait_ms=1.0).start()
    wedged, release = threading.Event(), threading.Event()
    inner = mb.servable

    def wedge(cols):
        wedged.set()
        release.wait(timeout=60)
        return inner(cols)

    mb.servable = wedge
    try:
        x = np.asarray(feats["x"])
        fut = mb.submit({"x": x[:1]}, 1)
        assert wedged.wait(timeout=30)
        with pytest.raises(EngineStalledError, match="park"):
            mb.close(timeout=0.2)
    finally:
        release.set()
        mb.close(timeout=30)
    assert np.asarray(fut.result(timeout=5)).shape[0] == 1


# ---------------------------------------------------------------------------
# satellite regressions: queue-full parity between the two paths
# ---------------------------------------------------------------------------

def test_microbatcher_queue_full_carries_measured_retry_after(tmp_path):
    """The :predict 429 now rides RetryAfterEstimator semantics (a
    measured hint, not the old hard-coded 1.0)."""
    import jax

    from distributed_tensorflow_example_tpu.config import TrainConfig
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.serving import (
        export_model, serving_signature)
    d = str(tmp_path / "predict")
    m = get_model("mlp", TrainConfig(model="mlp"))
    out = m.init(jax.random.key(0))
    params, extras = out if isinstance(out, tuple) else (out, {})
    export_model(m, params, extras, d, platforms=("cpu",))
    feats = serving_signature(m.dummy_batch(4))
    x = np.asarray(feats["x"])
    mb = MicroBatcher(load_servable(d), batch_max_size=1,
                      batch_max_wait_ms=1.0, max_queue=2).start()
    # wedge the dispatch so submissions pile into the bounded queue
    wedged, release = threading.Event(), threading.Event()
    inner = mb.servable

    def wedge(cols):
        wedged.set()
        release.wait(timeout=60)
        return inner(cols)

    mb.servable = wedge
    try:
        futs = [mb.submit({"x": x[:1]}, 1)]
        assert wedged.wait(timeout=30)
        futs += [mb.submit({"x": x[:1]}, 1) for _ in range(2)]
        with pytest.raises(QueueFullError) as e:
            mb.submit({"x": x[:1]}, 1)
        assert e.value.retry_after > 0
        release.set()
        for f in futs:                   # nothing queued was dropped
            assert np.asarray(f.result(timeout=60)).shape[0] == 1
    finally:
        release.set()
        mb.close()


def test_queue_full_status_and_headers_agree_across_paths(chaos_dir,
                                                          tmp_path):
    """429 + Retry-After must look the same whether the :generate
    engine or the :predict batcher said 'full'."""
    import jax

    from distributed_tensorflow_example_tpu.config import TrainConfig
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.serving import (
        export_model, serving_signature)
    dp = str(tmp_path / "predict")
    m = get_model("mlp", TrainConfig(model="mlp"))
    out = m.init(jax.random.key(0))
    params, extras = out if isinstance(out, tuple) else (out, {})
    export_model(m, params, extras, dp, platforms=("cpu",))
    feats = serving_signature(m.dummy_batch(4))

    def full(payload, request_id=None, trace=None):
        raise QueueFullError("full", retry_after=2.6)

    seen = {}
    for d, verb, payload in (
            (chaos_dir[0], "generate",
             {"inputs": {"input_ids": [[1, 2]]}}),
            (dp, "predict",
             {"inputs": {"x": np.asarray(feats["x"])[:1].tolist()}})):
        with PredictServer(d) as srv:
            setattr(srv, verb, full)
            try:
                _post(srv.port, srv.name, payload, verb=verb)
                raise AssertionError("QueueFullError not surfaced")
            except urllib.error.HTTPError as e:
                seen[verb] = (e.code, e.headers.get("Retry-After"))
    assert seen["generate"] == seen["predict"] == (429, "3"), seen


# ---------------------------------------------------------------------------
# satellite regressions: fault-seam inertness
# ---------------------------------------------------------------------------

def test_serving_seams_inert_when_silent(chaos_dir):
    """The armed-vs-plain parity harness (the PR-9 pattern): a
    registry whose rules never fire must leave the engine byte- AND
    dispatch-identical to no registry at all — so the inert-by-default
    None-check seams provably cost zero behavior. (No-registry ==
    pre-PR behavior is additionally pinned by the whole pre-existing
    parity suite running over the seamed engine.)"""
    d, vocab = chaos_dir
    prompts = serving_chaos.seeded_prompts(6, 7, vocab)

    def run(spec):
        if spec:
            faults.install(faults.parse_spec(spec, seed=0))
        try:
            eng = _engine(d)
            try:
                handles = [eng.submit(p, max_new=6) for p in prompts]
                outs = [h.result(timeout=120) for h in handles]
                s = eng.stats()
                return outs, (s["decode_steps"], s["prefills"],
                              s["requests_done"], s["redispatches"])
            finally:
                eng.close()
        finally:
            faults.install(None)

    plain = run(None)
    armed = run("engine.decode_step:step=999999;"
                "engine.prefill:step=999999;engine.admit:step=999999;"
                "pool.alloc:step=999999;http.read:step=999999;"
                "router.probe:step=999999;router.forward:step=999999;"
                "replica.crash:step=999999")
    assert plain == armed


def test_spec_seams_inert_when_silent(tmp_path):
    """The armed-vs-plain inertness harness extended to the SPEC path:
    an armed-but-silent fault registry over an engine running
    speculative decoding (verify dispatches probe the same
    engine.decode_step seam) must stay byte- and dispatch-identical —
    including the verify-dispatch and accept counters — to no registry
    at all."""
    sys.path.insert(0, os.path.join(ROOT, "experiments"))
    from serving_load import build_export, make_repetitive_requests

    d = str(tmp_path / "spec")
    vocab = build_export(d, prompt_len=8, max_new=16, slots=4, seed=0,
                         paged=True, block_size=4, spec_tokens=4)
    matrix = make_repetitive_requests(1, 4, prompt_len=8, max_new=12,
                                      vocab=vocab, seed=0)
    prompts = [p for row in matrix for p, _ in row]

    def run(spec):
        if spec:
            faults.install(faults.parse_spec(spec, seed=0))
        try:
            eng = _engine(d, spec_tokens=4)
            try:
                handles = [eng.submit(p, max_new=12) for p in prompts]
                outs = [h.result(timeout=120) for h in handles]
                s = eng.stats()
                return outs, (s["decode_steps"], s["verify_steps"],
                              s["prefills"], s["spec_proposed"],
                              s["spec_accepted"], s["requests_done"],
                              s["redispatches"])
            finally:
                eng.close()
        finally:
            faults.install(None)

    plain = run(None)
    armed = run("engine.decode_step:step=999999;"
                "engine.prefill:step=999999;engine.admit:step=999999;"
                "pool.alloc:step=999999")
    assert plain == armed
    # the workload genuinely exercised the spec path (else the parity
    # above would be vacuous)
    assert plain[1][1] > 0 and plain[1][4] > 0, plain[1]


# ---------------------------------------------------------------------------
# the HTTP failure surface
# ---------------------------------------------------------------------------

def test_http_deadline_ms_answers_504(chaos_dir):
    d, _ = chaos_dir
    with PredictServer(d) as srv:
        try:
            _post(srv.port, srv.name,
                  {"inputs": {"input_ids": [[1, 2, 3]]},
                   "max_new": 16, "deadline_ms": 1})
            raise AssertionError("1 ms deadline never expired")
        except urllib.error.HTTPError as e:
            assert e.code == 504
            assert "deadline" in json.loads(e.read())["error"]
        # the server keeps serving afterwards
        out = _post(srv.port, srv.name,
                    {"inputs": {"input_ids": [[1, 2, 3]]},
                     "max_new": 2})
        assert len(out["generations"][0]) == 2


def test_http_cancel_route(chaos_dir):
    """POST /cancel/<rid>: 404 for unknown ids; a live request's
    waiter gets 409 and the cancel itself 200."""
    d, _ = chaos_dir
    with PredictServer(d) as srv:
        code, body = _post_raw(srv.port, "/cancel/never-submitted")
        assert code == 404 and "never-submitted" in body["error"]

        waiter: dict = {}

        def post_long():
            try:
                waiter["ok"] = _post(srv.port, srv.name,
                                     {"inputs": {"input_ids": [[5, 6]]},
                                      "max_new": 16},
                                     request_id="cancel-me")
            except urllib.error.HTTPError as e:
                waiter["code"] = e.code
                waiter["err"] = json.loads(e.read())["error"]

        th = threading.Thread(target=post_long)
        th.start()
        deadline = time.monotonic() + 30

        def try_cancel():
            c, b = _post_raw(srv.port, "/cancel/cancel-me")
            return c == 200 and b == {"cancelled": "cancel-me"}

        while time.monotonic() < deadline and not try_cancel():
            time.sleep(0.005)
        th.join(timeout=60)
        assert waiter.get("code") == 409, waiter
        assert "cancelled" in waiter["err"]


def test_http_healthz(chaos_dir):
    d, _ = chaos_dir
    with PredictServer(d) as srv:
        code, body = _get(srv.port, "/healthz")
        assert code == 200 and body["status"] == "live"
        assert {"heartbeat_age_s", "stall_after_s", "queue_depth",
                "inflight", "draining"} <= set(body)
    # a watchdog threshold of zero makes ANY heartbeat age 'stalled':
    # /healthz must answer 503 so the LB stops routing here
    with PredictServer(d, stall_after_s=0.0) as srv:
        _wait(lambda: _get(srv.port, "/healthz")[0] == 503,
              what="healthz flipping to 503 at stall_after_s=0")
        code, body = _get(srv.port, "/healthz")
        assert code == 503 and body["status"] == "stalled"


def test_http_healthz_without_engine(tmp_path):
    import jax

    from distributed_tensorflow_example_tpu.config import TrainConfig
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.serving import export_model
    d = str(tmp_path / "predict")
    m = get_model("mlp", TrainConfig(model="mlp"))
    out = m.init(jax.random.key(0))
    params, extras = out if isinstance(out, tuple) else (out, {})
    export_model(m, params, extras, d, platforms=("cpu",))
    with PredictServer(d) as srv:          # no scheduler thread at all
        code, body = _get(srv.port, "/healthz")
        assert code == 200 and body["status"] == "live"


def test_http_draining_answers_503_with_retry_after(chaos_dir):
    d, _ = chaos_dir
    srv = PredictServer(d).start()
    try:
        bg: dict = {}

        def post_long():
            bg["out"] = _post(srv.port, srv.name,
                              {"inputs": {"input_ids": [[7, 8, 9]]},
                               "max_new": 16})

        th = threading.Thread(target=post_long)
        th.start()
        _wait(lambda: srv.engine.health()["inflight"] > 0,
              what="the long request going in flight")
        dr = threading.Thread(target=srv.engine.drain)
        dr.start()
        _wait(lambda: srv.engine.health()["draining"],
              what="drain flag")
        try:
            _post(srv.port, srv.name,
                  {"inputs": {"input_ids": [[1]]}, "max_new": 2})
            raise AssertionError("admission accepted during drain")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert int(e.headers["Retry-After"]) >= 1
            assert "drain" in json.loads(e.read())["error"]
        dr.join(timeout=120)
        th.join(timeout=120)
        # zero dropped: the in-flight request finished under the drain
        assert len(bg["out"]["generations"][0]) == 16
    finally:
        srv.stop(drain=False)


def test_http_read_fault_seam(chaos_dir):
    """The http.read seam: an injected body-read fault answers 400 —
    and once the one-shot rule is spent the server serves clean."""
    d, _ = chaos_dir
    with PredictServer(d) as srv:
        faults.install(faults.parse_spec("http.read:step=1", seed=0))
        try:
            try:
                _post(srv.port, srv.name,
                      {"inputs": {"input_ids": [[1, 2]]}, "max_new": 2})
                raise AssertionError("http.read fault never surfaced")
            except urllib.error.HTTPError as e:
                assert e.code == 400
                assert "injected fault" in json.loads(e.read())["error"]
            out = _post(srv.port, srv.name,
                        {"inputs": {"input_ids": [[1, 2]]},
                         "max_new": 2})
            assert len(out["generations"][0]) == 2
        finally:
            faults.install(None)


def test_cancel_during_block_pressure_deferral_not_lost(chaos_dir):
    """Review regression: a cancel accepted while its request is
    MID-ADMISSION must survive a block-pressure deferral (which
    re-queues the request and drops its in-flight id) — the
    _apply_cancellations queue sweep honors it at the next boundary
    instead of silently admitting the request later."""
    d, vocab = chaos_dir
    eng = _engine(d)
    orig_alloc = eng.blocks.alloc
    state = {"armed": True}

    def alloc(n):
        # the victim's first admission: a racing client cancels while
        # the request is in _inflight_ids, then the allocator reports
        # exhaustion so the engine re-queues it at the head
        if state["armed"] and eng._admitting is victim.req:
            state["armed"] = False
            assert eng.cancel(victim.request_id)
            from distributed_tensorflow_example_tpu.serving_batch \
                import BlocksExhaustedError
            raise BlocksExhaustedError("injected block pressure")
        return orig_alloc(n)

    try:
        # a long-running neighbor keeps _live non-empty, so the
        # exhaustion path DEFERS (re-queues) instead of failing loudly
        neighbor = eng.submit((np.arange(1, 8) % vocab)
                              .astype(np.int32), max_new=16)
        _wait(lambda: eng.stats()["live_slots"] == 1,
              what="neighbor going live")
        eng.blocks.alloc = alloc
        victim = eng.submit(np.array([3, 1, 4], np.int32), max_new=16)
        with pytest.raises(RequestCancelledError):
            victim.req.future.result(timeout=60)
        assert eng.stats()["cancelled"] == 1
        assert len(neighbor.result(timeout=120)) == 16  # undisturbed
    finally:
        eng.blocks.alloc = orig_alloc
        eng.close()


def test_http_multirow_failure_cancels_sibling_rows(chaos_dir):
    """Review regression: when one row of a multi-row :generate fails,
    the single-error response must not leave sibling rows decoding to
    max_new holding slots and blocks — they are cancelled before the
    error surfaces."""
    d, _ = chaos_dir
    with PredictServer(d) as srv:
        faults.install(faults.parse_spec("engine.admit:step=1", seed=0))
        try:
            try:
                _post(srv.port, srv.name,
                      {"inputs": {"input_ids": [[1, 2, 3],
                                                [4, 5, 6]]},
                       "max_new": 16})
                raise AssertionError("poisoned admission answered 200")
            except urllib.error.HTTPError as e:
                assert e.code == 500
            eng = srv.engine

            def settled():
                # live==0 + queue==0 alone is also true MID-admission
                # (popped, not yet live) — wait for both rows to be
                # terminally accounted for
                s = eng.stats()
                return (s["live_slots"] == 0
                        and s["queue_depth"] == 0
                        and s["cancelled"] + s["requests_failed"] >= 2)

            _wait(settled, what="both rows retiring")
            s = eng.stats()
            # nothing retired successfully: the poisoned row failed,
            # the sibling was CANCELLED well short of its max_new=16
            # (the leak would be it decoding to completion for nobody)
            assert s["requests_done"] == 0, s
            assert s["cancelled"] == 1 and s["requests_failed"] == 1, s
            assert s["tokens_out"] < 16, s
        finally:
            faults.install(None)


def test_stop_closes_listener_even_when_drain_stalls(chaos_dir):
    """Review regression: stop() on a wedged scheduler raises
    EngineStalledError — but the HTTP listener must STILL come down,
    or SIGTERM would leave an unkillable server refusing traffic."""
    d, _ = chaos_dir
    srv = PredictServer(d, drain_timeout_s=0.5).start()
    eng = srv.engine
    wedged, release = threading.Event(), threading.Event()
    orig = eng.sw.decode

    def wedge(feats):
        wedged.set()
        release.wait(timeout=60)
        return orig(feats)

    eng.sw.decode = wedge
    try:
        eng.submit(np.array([1, 2, 3], np.int32), max_new=8)
        assert wedged.wait(timeout=30)
        with pytest.raises(EngineStalledError):
            srv.stop()
        with pytest.raises(urllib.error.URLError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=2)
    finally:
        release.set()
        eng.close(timeout=30)


def test_async_decode_fault_escalates_to_pool_rebuild(chaos_dir):
    """Review regression: on an async backend a device fault surfaces
    at the blocking logits materialization, AFTER the dispatch donated
    the pool. The engine must still treat it as pool-consuming —
    engine-fatal fail-all + rebuild — NOT adopt the failed call's
    outputs, judge them alive, and retry a dispatch whose input
    buffers were deleted (which would serially evict every live slot
    as 'poisoned')."""
    d, vocab = chaos_dir
    eng = _engine(d)
    orig = eng.sw.decode
    armed = {"on": True}

    class _FailsOnRead:
        # numpy materialization raises — the async-error surface
        def __array__(self, dtype=None):
            raise RuntimeError("simulated async device fault")

    def decode(feats):
        out = orig(feats)          # REAL dispatch: pool donated
        if armed["on"]:
            armed["on"] = False
            return {**out, "logits": _FailsOnRead()}
        return out

    eng.sw.decode = decode
    try:
        handles = [eng.submit((np.arange(1, 4 + i) % vocab)
                              .astype(np.int32), max_new=6)
                   for i in range(2)]
        for h in handles:
            with pytest.raises(RuntimeError, match="scheduler step"):
                h.req.future.result(timeout=60)
        s = eng.stats()
        # engine-fatal, not quarantine: no bogus retry over deleted
        # buffers, no poisoned-eviction of innocent slots
        assert s["redispatches"] == 0, s
        # the rebuilt pool serves again
        out = eng.generate(np.array([5, 6], np.int32), timeout=120,
                           max_new=3)
        assert len(out) == 3
    finally:
        eng.sw.decode = orig
        eng.close()
