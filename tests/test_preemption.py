"""PreemptionHook: SIGTERM → finish the step, checkpoint, exit cleanly
(the Supervisor stop→save semantics; TPU maintenance-event handling)."""

import os
import signal

import jax
import numpy as np

from distributed_tensorflow_example_tpu.ckpt.checkpoint import (
    CheckpointManager)
from distributed_tensorflow_example_tpu.config import (CheckpointConfig,
                                                       DataConfig,
                                                       MeshShape,
                                                       OptimizerConfig,
                                                       TrainConfig)
from distributed_tensorflow_example_tpu.data.mnist import synthetic_mnist
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
from distributed_tensorflow_example_tpu.train import hooks as hooks_lib
from distributed_tensorflow_example_tpu.train.trainer import Trainer


class _SigtermAt(hooks_lib.Hook):
    def __init__(self, at_step: int):
        self.at_step = at_step

    def after_step(self, trainer, step, metrics):
        if step == self.at_step:
            os.kill(os.getpid(), signal.SIGTERM)


def _trainer(ckpt_dir, steps=50, extra=None):
    cfg = TrainConfig(
        model="mlp", train_steps=steps, mesh=MeshShape(data=4),
        data=DataConfig(batch_size=64, seed=3),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        checkpoint=CheckpointConfig(directory=ckpt_dir, save_steps=100),
        seed=7)
    data = synthetic_mnist(num_train=640, num_test=64, seed=0)
    model = get_model("mlp", cfg)
    return Trainer(model, cfg, {"x": data["train_x"], "y": data["train_y"]},
                   mesh=local_mesh(4), process_index=0, num_processes=1,
                   hooks=extra or [])


def test_sigterm_checkpoints_and_stops(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    t = _trainer(ckpt, steps=50, extra=[_SigtermAt(3)])
    state, summary = t.train()
    t.close()

    # stopped at the boundary after the signal, far short of train_steps
    stopped_at = summary["final_step"]
    assert 3 <= stopped_at <= 4, stopped_at
    # the stop checkpoint exists and restores to the same step
    mgr = CheckpointManager(ckpt)
    assert mgr.latest_step() == stopped_at
    # handlers restored after end()
    assert signal.getsignal(signal.SIGTERM) == signal.SIG_DFL

    # resume runs to completion untouched by the old signal
    t2 = _trainer(ckpt, steps=stopped_at + 5)
    s2, summary2 = t2.train()
    t2.close()
    assert summary2["final_step"] == stopped_at + 5
    assert int(jax.device_get(s2.step)) == stopped_at + 5


def test_no_signal_trains_to_completion(tmp_path):
    t = _trainer(str(tmp_path / "ckpt"), steps=6)
    _, summary = t.train()
    t.close()
    assert summary["final_step"] == 6
    assert np.isfinite(summary["final_metrics"]["loss"])
