"""Failure & recovery semantics (SURVEY.md §3.5, §4 item 4, §5.3).

The reference's story: worker crash tolerated via spare sync tokens, chief
restart = recover_session from the last checkpoint, workers poll until
ready. The TPU-native story is restart-from-latest-checkpoint with exact
resume: state restores bit-identically and the data stream fast-forwards,
so a killed-and-restarted run converges to the SAME final state as an
uninterrupted one — a stronger guarantee than the reference's (its
feed_dict stream restarted from scratch on recovery).
"""

import jax
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import (CheckpointConfig,
                                                       DataConfig, MeshShape,
                                                       OptimizerConfig,
                                                       TrainConfig)
from distributed_tensorflow_example_tpu.data.mnist import synthetic_mnist
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
from distributed_tensorflow_example_tpu.train.trainer import Trainer


def _cfg(steps, ckpt_dir=None, save_steps=0):
    return TrainConfig(
        model="mlp", train_steps=steps, mesh=MeshShape(data=4),
        data=DataConfig(batch_size=64, seed=3),
        optimizer=OptimizerConfig(name="momentum", learning_rate=0.1),
        checkpoint=CheckpointConfig(directory=ckpt_dir,
                                    save_steps=save_steps),
        seed=7)


def _trainer(cfg, data):
    model = get_model("mlp", cfg)
    return Trainer(model, cfg,
                   {"x": data["train_x"], "y": data["train_y"]},
                   mesh=local_mesh(4), process_index=0, num_processes=1)


def _params(state):
    return jax.tree_util.tree_map(np.asarray, jax.device_get(state.params))


def test_kill_restore_resume_matches_uninterrupted(tmp_path):
    """Crash at step 10, restart, run to 20 == straight run to 20."""
    data = synthetic_mnist(num_train=640, num_test=64, seed=0)

    # uninterrupted reference run
    t_ref = _trainer(_cfg(20), data)
    s_ref, _ = t_ref.train()

    # run A: crashes (stops) at step 10, checkpointing every 5
    ckpt = str(tmp_path / "ckpt")
    t_a = _trainer(_cfg(10, ckpt, save_steps=5), data)
    s_a, _ = t_a.train()
    assert int(jax.device_get(s_a.step)) == 10

    # run B: fresh process restores at 10 (restore-or-init), resumes to 20
    t_b = _trainer(_cfg(20, ckpt, save_steps=5), data)
    t_b.initialize()
    assert t_b.start_step == 10, "must restore, not re-init"
    s_b, _ = t_b.train()
    assert int(jax.device_get(s_b.step)) == 20

    ref, got = _params(s_ref), _params(s_b)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7),
        ref, got)


def test_restore_or_init_fresh_when_no_checkpoint(tmp_path):
    data = synthetic_mnist(num_train=256, num_test=32, seed=0)
    t = _trainer(_cfg(3, str(tmp_path / "empty")), data)
    t.initialize()
    assert t.start_step == 0


def test_loader_fast_forward_exactness():
    """Batches after fast-forward == batches of a full replay."""
    from distributed_tensorflow_example_tpu.data.loader import make_loader
    rs = np.random.RandomState(0)
    arrays = {"x": rs.rand(96, 3).astype(np.float32),
              "y": np.arange(96, dtype=np.int32)}
    full = make_loader(arrays, 16, seed=9)
    replay = [next(full) for _ in range(11)]       # 6 steps/epoch
    ff = make_loader(arrays, 16, seed=9, start_step=7)
    for want in replay[7:]:
        got = next(ff)
        np.testing.assert_array_equal(want["x"], got["x"])
        np.testing.assert_array_equal(want["y"], got["y"])


def test_loader_fast_forward_native_parity():
    from distributed_tensorflow_example_tpu.data import native
    if not native.available():
        pytest.skip("native loader not built")
    from distributed_tensorflow_example_tpu.data.loader import make_loader
    rs = np.random.RandomState(0)
    arrays = {"x": rs.rand(64, 3).astype(np.float32),
              "y": np.arange(64, dtype=np.int32)}
    py = make_loader(arrays, 16, seed=4, start_step=5)
    nat = make_loader(arrays, 16, seed=4, start_step=5, native=True)
    for _ in range(4):
        a, b = next(py), next(nat)
        np.testing.assert_array_equal(a["x"], b["x"])
