"""Pipeline-parallelism tests (VERDICT r2 task #5: deliver PP).

The parity claim: GPipe execution over a ``pipe`` mesh axis — microbatches
flowing stage-to-stage via ppermute — computes the SAME function as the
unpartitioned block stack, for outputs, loss, and gradients; and it
composes with sync data parallelism (a {data, pipe} mesh trains
equivalently to the pure-DP mesh).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import (MeshShape,
                                                       OptimizerConfig,
                                                       TrainConfig)
from distributed_tensorflow_example_tpu.models import get_model, list_models
from distributed_tensorflow_example_tpu.models.pipe_mlp import (PipeMlp,
                                                                PipeMlpConfig)
from distributed_tensorflow_example_tpu.parallel import pipeline
from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
    SyncReplicas)
from distributed_tensorflow_example_tpu.train.optimizers import make_optimizer


def _stage_fn(stacked, x, mb_idx=0):
    def body(h, blk):
        return h + jax.nn.relu(h @ blk["kernel"] + blk["bias"]), None
    out, _ = jax.lax.scan(body, x, stacked)
    return out


def _stacked_params(L=4, H=16, seed=0):
    rs = np.random.RandomState(seed)
    return {
        "kernel": jnp.asarray(rs.randn(L, H, H).astype(np.float32) * 0.3),
        "bias": jnp.asarray(rs.randn(L, H).astype(np.float32) * 0.1),
    }


# ---------------------------------------------------------------------------
# core: pipelined == sequential
# ---------------------------------------------------------------------------

def test_pipeline_matches_sequential(cpu8):
    mesh = local_mesh(4, {"pipe": 4})
    params = _stacked_params(L=4, H=16)
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(24, 16).astype(np.float32))

    piped = pipeline.make_pipeline(mesh, _stage_fn, num_microbatches=3)
    got = jax.jit(piped)(params, x)
    want = pipeline.sequential_blocks(_stage_fn, params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_two_stages_multi_block(cpu8):
    """L/P > 1: each stage runs 2 consecutive blocks."""
    mesh = local_mesh(2, {"pipe": 2})
    params = _stacked_params(L=4, H=8, seed=2)
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(8, 8).astype(np.float32))
    piped = pipeline.make_pipeline(mesh, _stage_fn, num_microbatches=4)
    np.testing.assert_allclose(
        np.asarray(jax.jit(piped)(params, x)),
        np.asarray(pipeline.sequential_blocks(_stage_fn, params, x)),
        rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential(cpu8):
    """The GPipe backward schedule falls out of jax.grad: gradients through
    the ppermute ring equal the unpartitioned stack's gradients."""
    mesh = local_mesh(4, {"pipe": 4})
    params = _stacked_params(L=4, H=16, seed=3)
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(16, 16).astype(np.float32))
    piped = pipeline.make_pipeline(mesh, _stage_fn, num_microbatches=4)

    g_pipe = jax.jit(jax.grad(
        lambda p: jnp.sum(jnp.square(piped(p, x)))))(params)
    g_seq = jax.jit(jax.grad(lambda p: jnp.sum(jnp.square(
        pipeline.sequential_blocks(_stage_fn, p, x)))))(params)
    for kp, ks in zip(jax.tree_util.tree_leaves(g_pipe),
                      jax.tree_util.tree_leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(kp), np.asarray(ks),
                                   rtol=2e-4, atol=1e-5)


def test_pipeline_microbatch_divisibility_error(cpu8):
    mesh = local_mesh(4, {"pipe": 4})
    params = _stacked_params(L=4, H=8)
    x = jnp.zeros((10, 8))   # 10 not divisible by 3 microbatches
    piped = pipeline.make_pipeline(mesh, _stage_fn, num_microbatches=3)
    with pytest.raises(ValueError, match="not divisible"):
        piped(params, x)


def test_pipeline_block_count_divisibility_error(cpu8):
    mesh = local_mesh(4, {"pipe": 4})
    params = _stacked_params(L=6, H=8)   # 6 blocks over 4 stages
    piped = pipeline.make_pipeline(mesh, _stage_fn, num_microbatches=2)
    with pytest.raises(ValueError, match="not divisible"):
        piped(params, jnp.zeros((8, 8)))


# ---------------------------------------------------------------------------
# PipeMlp model
# ---------------------------------------------------------------------------

def test_pipe_mlp_registered():
    assert "pipe_mlp" in list_models()
    m = get_model("pipe_mlp", TrainConfig(model="pipe_mlp"))
    assert isinstance(m, PipeMlp)


def _mnist_batch(bs=64, seed=0):
    rs = np.random.RandomState(seed)
    return {"x": rs.rand(bs, 784).astype(np.float32),
            "y": rs.randint(0, 10, size=(bs,), dtype=np.int32)}


def test_pipe_mlp_bound_matches_unbound(cpu8):
    mesh = local_mesh(4, {"pipe": 4})
    m = PipeMlp(PipeMlpConfig(blocks=4, microbatches=4))
    params = m.init(jax.random.key(0))
    batch = _mnist_batch(32)

    logits_seq, _ = m.apply(params, {}, batch)
    m.bind_mesh(mesh)
    assert m._pipelined is not None
    logits_pipe, _ = jax.jit(lambda p: m.apply(p, {}, batch))(params)
    np.testing.assert_allclose(np.asarray(logits_pipe),
                               np.asarray(logits_seq),
                               rtol=1e-5, atol=1e-6)


def test_pipe_mlp_dp_pipe_step_equals_pure_dp(cpu8):
    """One SyncReplicas step on {data:2, pipe:4} == one step on {data:8}
    — pipelining must not change training semantics."""
    batch = _mnist_batch(64, seed=4)

    def one_step(mesh_shape_dict, mesh_shape):
        mesh = local_mesh(8, mesh_shape_dict)
        m = PipeMlp(PipeMlpConfig(blocks=4, microbatches=4))
        m.bind_mesh(mesh)
        tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
        sync = SyncReplicas(m.loss, tx, mesh,
                            rules=m.sharding_rules(mesh_shape))
        state = sync.init(m.init, seed=0)
        state, metrics = sync.step(state, sync.shard_batch(batch))
        return (jax.device_get(state.params), float(metrics["loss"]))

    p_pp, loss_pp = one_step({"data": 2, "pipe": 4},
                             MeshShape(data=2, pipe=4))
    p_dp, loss_dp = one_step({"data": 8}, MeshShape(data=8))
    assert abs(loss_pp - loss_dp) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(p_pp),
                    jax.tree_util.tree_leaves(p_dp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)


def test_pipe_mlp_learns(cpu8):
    mesh = local_mesh(8, {"data": 2, "pipe": 4})
    m = PipeMlp(PipeMlpConfig(blocks=4, microbatches=4))
    m.bind_mesh(mesh)
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.2))
    sync = SyncReplicas(m.loss, tx, mesh,
                        rules=m.sharding_rules(MeshShape(data=2, pipe=4)))
    state = sync.init(m.init, seed=0)
    losses = []
    for i in range(12):
        b = _mnist_batch(64, seed=i % 3)
        state, metrics = sync.step(state, sync.shard_batch(b))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


def test_pipe_mlp_cli_trains(tmp_path, cpu8):
    """End-to-end: pipeline parallelism reachable from the reference CLI."""
    from distributed_tensorflow_example_tpu.cli.train import main
    rc = main(["--model=pipe_mlp", "--mesh=data=2,pipe=4",
               "--train_steps=6", "--batch_size=64",
               "--log_every_steps=3", "--learning_rate=0.1"])
    assert rc == 0
