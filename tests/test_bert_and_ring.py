"""BERT MLM + ring attention + tensor parallelism tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import (MeshShape,
                                                       OptimizerConfig,
                                                       TrainConfig)
from distributed_tensorflow_example_tpu.data.bert_data import (
    MASK, apply_mlm_masking, get_bert_data, synthetic_corpus)
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.models.bert import Bert, BertConfig
from distributed_tensorflow_example_tpu.ops.attention import (
    multi_head_attention)
from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
from distributed_tensorflow_example_tpu.parallel.ring_attention import (
    make_ring_attention)
from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
    SyncReplicas)
from distributed_tensorflow_example_tpu.train.optimizers import make_optimizer


# ---------------------------------------------------------------------------
# ring attention == reference attention
# ---------------------------------------------------------------------------

def _qkv(b=2, s=32, h=4, d=16, seed=0):
    rs = np.random.RandomState(seed)
    return tuple(rs.randn(b, s, h, d).astype(np.float32) * 0.3
                 for _ in range(3))


def test_ring_attention_matches_reference_full():
    mesh = local_mesh(8, {"seq": 8})
    q, k, v = _qkv()
    want = multi_head_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v))
    ring = make_ring_attention(mesh)
    got = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_matches_reference_causal():
    mesh = local_mesh(4, {"seq": 4})
    q, k, v = _qkv(s=16)
    want = multi_head_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v), causal=True)
    ring = make_ring_attention(mesh, causal=True)
    got = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_matches_reference_padding_mask():
    mesh = local_mesh(4, {"seq": 4})
    q, k, v = _qkv(s=16)
    mask = np.ones((2, 16), np.int32)
    mask[:, 12:] = 0                      # last block fully padded
    want = multi_head_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v),
                                mask=jnp.asarray(mask)[:, None, None, :])
    ring = make_ring_attention(mesh)
    got = ring(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(got)[:, :12], np.asarray(want)[:, :12],
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_inside_jit():
    mesh = local_mesh(4, {"seq": 4})
    ring = make_ring_attention(mesh)
    q, k, v = _qkv(s=16)
    out = jax.jit(lambda a, b, c: ring(a, b, c))(q, k, v)
    want = multi_head_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


# ---------------------------------------------------------------------------
# MLM data pipeline
# ---------------------------------------------------------------------------

def test_mlm_masking_properties():
    seqs = synthetic_corpus(64, 64, vocab_size=1000, seed=0)
    b = apply_mlm_masking(seqs, vocab_size=1000, max_predictions=10, seed=1)
    assert b["input_ids"].shape == (64, 64)
    assert b["masked_positions"].shape == (64, 10)
    # labels store the ORIGINAL token at each masked position
    for i in range(8):
        w = b["masked_weights"][i].astype(bool)
        pos = b["masked_positions"][i][w]
        np.testing.assert_array_equal(b["masked_labels"][i][w],
                                      seqs[i][pos])
    # ~80% of masked inputs are [MASK]
    w = b["masked_weights"].astype(bool)
    pos = b["masked_positions"]
    masked_inputs = np.take_along_axis(b["input_ids"], pos, axis=1)[w]
    frac_mask = np.mean(masked_inputs == MASK)
    assert 0.6 < frac_mask < 0.95
    # deterministic
    b2 = apply_mlm_masking(seqs, vocab_size=1000, max_predictions=10, seed=1)
    np.testing.assert_array_equal(b["input_ids"], b2["input_ids"])


def test_get_bert_data_shapes():
    tr, te = get_bert_data(None, vocab_size=1000, seq_len=32,
                           num_train=16, num_test=8)
    assert tr["input_ids"].shape == (16, 32)
    assert te["masked_weights"].shape[0] == 8


# ---------------------------------------------------------------------------
# BERT model
# ---------------------------------------------------------------------------

def _tiny():
    return get_model("bert_tiny", TrainConfig(model="bert_tiny"))


def test_bert_tiny_forward_and_loss():
    m = _tiny()
    params = m.init(jax.random.key(0))
    batch = m.dummy_batch(2)
    logits, _ = m.apply(params, {}, batch)
    assert logits.shape == (2, m.cfg.max_predictions, m.cfg.vocab_size)
    loss, (aux, _) = m.loss(params, {}, batch, jax.random.key(1))
    assert np.isfinite(float(loss))
    assert 0.0 <= float(aux["mlm_accuracy"]) <= 1.0


def test_bert_base_param_count():
    m = get_model("bert", TrainConfig(model="bert"))
    abstract = jax.eval_shape(lambda: m.init(jax.random.key(0)))
    n = sum(int(np.prod(x.shape))
            for x in jax.tree_util.tree_leaves(abstract))
    # BERT-base: ~110M params (incl. MLM head, untied decoder excluded)
    assert 105e6 < n < 115e6, n


def test_bert_tiny_tp_step_matches_replicated():
    """Tensor-parallel (model=2) step == fully replicated step."""
    m = _tiny()
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
    batch = m.dummy_batch(8)

    mesh_rep = local_mesh(1)
    sync_rep = SyncReplicas(m.loss, tx, mesh_rep)
    s_rep = sync_rep.init(m.init, seed=0)

    mesh_tp = local_mesh(4, {"data": 2, "model": 2})
    rules = m.sharding_rules(MeshShape(data=2, model=2))
    sync_tp = SyncReplicas(m.loss, tx, mesh_tp, rules=rules)
    s_tp = sync_tp.init(m.init, seed=0)

    s_rep, m_rep = sync_rep.step(s_rep, sync_rep.shard_batch(batch))
    s_tp, m_tp = sync_tp.step(s_tp, sync_tp.shard_batch(batch))
    np.testing.assert_allclose(float(m_rep["loss"]), float(m_tp["loss"]),
                               rtol=1e-4)
    w_rep = np.asarray(jax.device_get(
        s_rep.params["layer_0"]["attn"]["q"]["kernel"]))
    w_tp = np.asarray(jax.device_get(
        s_tp.params["layer_0"]["attn"]["q"]["kernel"]))
    np.testing.assert_allclose(w_rep, w_tp, rtol=1e-4, atol=1e-6)


def test_bert_tiny_ring_attention_model(cpu8):
    """BERT with seq-parallel ring attention trains and matches xla attn."""
    mesh = local_mesh(8, {"data": 2, "seq": 4})
    base = BertConfig.tiny()
    base.dropout = 0.0
    m_ring = Bert(base, attention_fn=make_ring_attention(mesh))
    m_std = Bert(base)
    params = m_std.init(jax.random.key(0))
    batch = m_std.dummy_batch(4)
    l_std, _ = m_std.loss(params, {}, batch, jax.random.key(1))
    l_ring, _ = m_ring.loss(params, {}, batch, jax.random.key(1))
    np.testing.assert_allclose(float(l_std), float(l_ring), rtol=1e-4)


def test_bert_tiny_learns(cpu8):
    mesh = local_mesh(8)
    cfg = BertConfig.tiny()
    cfg.dropout = 0.0
    m = Bert(cfg)
    tx = make_optimizer(OptimizerConfig(name="adam", learning_rate=1e-3))
    sync = SyncReplicas(m.loss, tx, mesh)
    state = sync.init(m.init, seed=0)
    tr, _ = get_bert_data(None, vocab_size=cfg.vocab_size, seq_len=64,
                          num_train=64, num_test=8)
    losses = []
    for i in range(15):
        lo = (i % 2) * 32
        b = {k: v[lo:lo + 32] for k, v in tr.items()}
        state, metr = sync.step(state, sync.shard_batch(b))
        losses.append(float(metr["loss"]))
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_gradient_parity(causal):
    """grads THROUGH the ring (scan of ppermutes) == grads of the XLA
    reference on the 8-device mesh (VERDICT r1 weak #5: forward-only
    parity was not enough)."""
    mesh = local_mesh(8, {"seq": 8})
    q, k, v = _qkv(s=32)
    ring = make_ring_attention(mesh, causal=causal)

    def loss_ring(q, k, v):
        o = ring(q, k, v)
        return jnp.sum(jnp.square(o.astype(jnp.float32)))

    def loss_ref(q, k, v):
        o = multi_head_attention(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v), causal=causal)
        return jnp.sum(jnp.square(o.astype(jnp.float32)))

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for gr, gx in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gx),
                                   rtol=5e-4, atol=5e-5)


def test_ring_attention_gradient_parity_with_mask():
    mesh = local_mesh(4, {"seq": 4})
    q, k, v = _qkv(s=16)
    mask = np.ones((2, 16), np.int32)
    mask[:, 12:] = 0
    ring = make_ring_attention(mesh)

    def loss_ring(q, k, v):
        o = ring(q, k, v, mask=mask)
        return jnp.sum(jnp.square(o.astype(jnp.float32)[:, :12]))

    def loss_ref(q, k, v):
        o = multi_head_attention(q, k, v,
                                 mask=jnp.asarray(mask)[:, None, None, :])
        return jnp.sum(jnp.square(o.astype(jnp.float32)[:, :12]))

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for gr, gx in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gx),
                                   rtol=5e-4, atol=5e-5)


def test_bert_large_registered():
    """bert_large: BERT-large shape in the registry (24x1024x16; measured
    59.7% MFU @ b64 on the v5e chip — BASELINE.md model-zoo row)."""
    from distributed_tensorflow_example_tpu.config import TrainConfig
    from distributed_tensorflow_example_tpu.models import get_model
    m = get_model("bert_large", TrainConfig(model="bert_large"))
    assert (m.cfg.hidden, m.cfg.layers, m.cfg.heads,
            m.cfg.intermediate) == (1024, 24, 16, 4096)
    assert m.cfg.vocab_size == 30522
