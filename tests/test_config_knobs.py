"""Every config field must be load-bearing (VERDICT r2 task #7):
``param_dtype`` governs parameter storage dtype in every model family,
and ``total_num_replicas`` mismatches raise the documented hard error.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import (OptimizerConfig,
                                                       SyncConfig,
                                                       TrainConfig)
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
    SyncReplicas)
from distributed_tensorflow_example_tpu.train.optimizers import make_optimizer


@pytest.mark.parametrize("name", ["mlp", "lenet", "resnet20", "bert_tiny",
                                  "moe_bert_tiny"])
def test_param_dtype_bf16_reaches_every_model(name):
    cfg = TrainConfig(model=name, param_dtype="bfloat16")
    m = get_model(name, cfg)
    out = m.init(jax.random.key(0))
    params = out[0] if isinstance(out, tuple) else out
    for leaf in jax.tree_util.tree_leaves(params):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.bfloat16, leaf.dtype
    if isinstance(out, tuple):
        # BN running stats accumulate across steps: they must stay f32
        for leaf in jax.tree_util.tree_leaves(out[1]):
            assert leaf.dtype == jnp.float32


def test_param_dtype_default_f32():
    m = get_model("mlp", TrainConfig(model="mlp"))
    params = m.init(jax.random.key(0))
    assert params["fc1"]["kernel"].dtype == jnp.float32


def test_param_dtype_bf16_still_trains():
    m = get_model("mlp", TrainConfig(model="mlp", param_dtype="bfloat16"))
    mesh = local_mesh(1)
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
    sync = SyncReplicas(m.loss, tx, mesh)
    state = sync.init(m.init, seed=0)
    b = m.dummy_batch(8)
    losses = []
    for _ in range(5):
        state, metr = sync.step(state, sync.shard_batch(b))
        losses.append(float(metr["loss"]))
    assert state.params["fc1"]["kernel"].dtype == jnp.bfloat16
    assert np.isfinite(losses[-1]) and losses[-1] < losses[0]


@pytest.mark.parametrize("opt", ["momentum", "adam", "adamw"])
def test_moment_dtype_bf16_lands_in_opt_state(opt):
    m = get_model("mlp", TrainConfig(model="mlp"))
    mesh = local_mesh(1)
    tx = make_optimizer(OptimizerConfig(name=opt, learning_rate=1e-3,
                                        moment_dtype="bfloat16"))
    sync = SyncReplicas(m.loss, tx, mesh)
    state = sync.init(m.init, seed=0)
    dtypes = {np.dtype(l.dtype)
              for l in jax.tree_util.tree_leaves(state.opt_state)
              if hasattr(l, "dtype") and jnp.issubdtype(l.dtype,
                                                        jnp.floating)}
    assert np.dtype(jnp.bfloat16) in dtypes, dtypes
    if opt in ("adam", "adamw"):
        # nu must STAY f32: its sqrt scales the update directly
        assert np.dtype(np.float32) in dtypes, dtypes
    b = m.dummy_batch(8)
    losses = []
    for _ in range(5):
        state, metr = sync.step(state, sync.shard_batch(b))
        losses.append(float(metr["loss"]))
    assert np.isfinite(losses[-1]) and losses[-1] < losses[0]


def test_moment_dtype_bf16_checkpoint_roundtrip(tmp_path):
    from distributed_tensorflow_example_tpu.ckpt.checkpoint import (
        CheckpointManager)
    m = get_model("mlp", TrainConfig(model="mlp"))
    mesh = local_mesh(1)
    tx = make_optimizer(OptimizerConfig(name="adam", learning_rate=1e-3,
                                        moment_dtype="bfloat16"))
    sync = SyncReplicas(m.loss, tx, mesh)
    state = sync.init(m.init, seed=0)
    state, _ = sync.step(state, sync.shard_batch(m.dummy_batch(8)))
    for sharded in (False, True):
        mgr = CheckpointManager(str(tmp_path / f"s{sharded}"),
                                sharded=sharded)
        mgr.save(state, 1)
        restored = mgr.restore(jax.tree_util.tree_map(lambda x: x, state), 1)
        for a, b in zip(jax.tree_util.tree_leaves(state.opt_state),
                        jax.tree_util.tree_leaves(restored.opt_state)):
            assert a.dtype == b.dtype
            assert jnp.array_equal(a, b)


def test_default_moment_dtype_stays_f32_under_bf16_params():
    """moment_dtype='float32' must PIN mu to f32 even when params are
    bf16 (optax's None default would silently follow the param dtype)."""
    m = get_model("mlp", TrainConfig(model="mlp", param_dtype="bfloat16"))
    mesh = local_mesh(1)
    tx = make_optimizer(OptimizerConfig(name="adam", learning_rate=1e-3))
    sync = SyncReplicas(m.loss, tx, mesh)
    state = sync.init(m.init, seed=0)
    import optax
    adam_states = [s for s in jax.tree_util.tree_leaves(
        state.opt_state,
        is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState))
        if isinstance(s, optax.ScaleByAdamState)]
    mu_leaves = jax.tree_util.tree_leaves([s.mu for s in adam_states])
    assert mu_leaves
    for l in mu_leaves:
        assert l.dtype == jnp.float32, l.dtype


def test_prng_impl_rbg_threads_through_training_and_checkpoint(tmp_path):
    """--prng_impl rbg: the key impl reaches the state rng, training
    runs, and BOTH checkpoint formats restore the impl (wrap_key_data
    under the wrong impl would mis-size or silently change the random
    stream)."""
    from distributed_tensorflow_example_tpu.ckpt.checkpoint import (
        CheckpointManager)
    m = get_model("bert_tiny", TrainConfig(model="bert_tiny"))
    mesh = local_mesh(1)
    tx = make_optimizer(OptimizerConfig(name="adamw", learning_rate=1e-3))
    sync = SyncReplicas(m.loss, tx, mesh)
    state = sync.init(m.init, seed=0, prng_impl="rbg")
    assert str(jax.random.key_impl(state.rng)) == "rbg"
    b = m.dummy_batch(4)
    losses = []
    for _ in range(3):
        state, metr = sync.step(state, sync.shard_batch(b))
        losses.append(float(metr["loss"]))
    assert np.isfinite(losses[-1]) and losses[-1] < losses[0]

    for sharded in (False, True):
        mgr = CheckpointManager(str(tmp_path / f"s{sharded}"),
                                sharded=sharded)
        mgr.save(state, 1)
        restored = mgr.restore(jax.tree_util.tree_map(lambda x: x, state),
                               1)
        assert str(jax.random.key_impl(restored.rng)) == "rbg"
        # identical continuation: the stream must not fork on restore
        np.testing.assert_array_equal(
            jax.random.key_data(jax.random.fold_in(state.rng, 9)),
            jax.random.key_data(jax.random.fold_in(restored.rng, 9)))


def test_label_smoothing_matches_smoothed_onehot_oracle():
    """The gather-form smoothed xent must equal xent against the
    explicitly smoothed one-hot distribution."""
    from distributed_tensorflow_example_tpu.ops import losses
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(8, 10).astype(np.float32))
    labels = jnp.asarray(rs.randint(0, 10, 8).astype(np.int32))
    eps = 0.1
    got = losses.softmax_xent_int_labels(logits, labels,
                                         label_smoothing=eps)
    onehot = jax.nn.one_hot(labels, 10)
    smoothed = (1 - eps) * onehot + eps / 10.0
    want = losses.softmax_xent(logits, smoothed)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-6)
    # eps=0 equals plain one-hot xent (continuity at the boundary)
    np.testing.assert_allclose(
        float(losses.softmax_xent_int_labels(logits, labels)),
        float(losses.softmax_xent(logits, onehot)), rtol=1e-6)
    with pytest.raises(ValueError, match="label_smoothing"):
        losses.softmax_xent_int_labels(logits, labels, label_smoothing=1.0)


def test_label_smoothing_reaches_resnet():
    cfg = TrainConfig(model="resnet20", label_smoothing=0.1)
    m = get_model("resnet20", cfg)
    assert m.label_smoothing == 0.1
    # default off
    assert get_model("resnet20",
                     TrainConfig(model="resnet20")).label_smoothing == 0.0


def test_piecewise_schedule():
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_schedule)
    sched = make_schedule(OptimizerConfig(
        name="momentum", learning_rate=0.4, decay_schedule="piecewise",
        decay_boundaries=(10, 20), decay_factor=0.1))
    assert float(sched(0)) == pytest.approx(0.4)
    assert float(sched(15)) == pytest.approx(0.04)
    assert float(sched(25)) == pytest.approx(0.004)
    with pytest.raises(ValueError, match="decay_boundaries"):
        make_schedule(OptimizerConfig(decay_schedule="piecewise"))


def test_piecewise_boundaries_are_absolute_under_warmup():
    """join_schedules rebases the post-warmup schedule, so boundaries
    must be shifted at construction — a drop at step 100 with 50 warmup
    steps must land at 100, not 150."""
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_schedule)
    sched = make_schedule(OptimizerConfig(
        name="momentum", learning_rate=0.4, decay_schedule="piecewise",
        decay_boundaries=(100,), decay_factor=0.1, warmup_steps=50))
    assert float(sched(99)) == pytest.approx(0.4)
    assert float(sched(100)) == pytest.approx(0.04)
    with pytest.raises(ValueError, match="warmup"):
        make_schedule(OptimizerConfig(
            decay_schedule="piecewise", decay_boundaries=(30,),
            warmup_steps=50))


@pytest.mark.parametrize("opt", ["adamw", "momentum"])
def test_weight_decay_mask_excludes_1d(opt):
    """The standard decay recipe: matrices decay, biases/LN scales do
    not. Zero grads make the adam/momentum term exactly 0, so lr=1.0
    with wd=0.1 cleanly isolates the decay term: a decayed leaf shrinks
    and an excluded one stays frozen."""
    import optax

    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)
    params = {"kernel": jnp.ones((4, 4)), "bias": jnp.ones((4,))}
    grads = jax.tree_util.tree_map(jnp.zeros_like, params)
    tx = make_optimizer(OptimizerConfig(name=opt, learning_rate=1.0,
                                        weight_decay=0.1))
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    new = optax.apply_updates(params, updates)
    assert float(jnp.max(jnp.abs(new["kernel"] - 1.0))) > 0   # decayed
    np.testing.assert_array_equal(np.asarray(new["bias"]),
                                  np.ones(4))                  # excluded

    tx_all = make_optimizer(OptimizerConfig(name=opt, learning_rate=1.0,
                                            weight_decay=0.1,
                                            wd_mask="all"))
    updates, _ = tx_all.update(grads, tx_all.init(params), params)
    new = optax.apply_updates(params, updates)
    assert float(jnp.max(jnp.abs(new["bias"] - 1.0))) > 0      # decays too


def test_wd_mask_rejects_garbage():
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)
    with pytest.raises(ValueError, match="wd_mask"):
        make_optimizer(OptimizerConfig(name="adamw", weight_decay=0.1,
                                       wd_mask="bogus"))


def test_exponential_schedule():
    """tf.train.exponential_decay parity: lr * rate^(step/decay_steps),
    continuous (staircase off)."""
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_schedule)
    sched = make_schedule(OptimizerConfig(
        learning_rate=0.8, decay_schedule="exponential",
        decay_steps=100, decay_factor=0.5))
    assert float(sched(0)) == pytest.approx(0.8)
    assert float(sched(100)) == pytest.approx(0.4)
    assert float(sched(200)) == pytest.approx(0.2)
    assert float(sched(50)) == pytest.approx(0.8 * 0.5 ** 0.5, rel=1e-5)
    with pytest.raises(ValueError, match="decay_steps"):
        make_schedule(OptimizerConfig(decay_schedule="exponential"))
    # absolute-step contract under warmup (same rule as piecewise): at
    # absolute step 200 with warmup 100, the tf formula gives rate^2
    warm = make_schedule(OptimizerConfig(
        learning_rate=0.8, decay_schedule="exponential",
        decay_steps=100, decay_factor=0.5, warmup_steps=100))
    assert float(warm(200)) == pytest.approx(0.2, rel=1e-5)
    assert float(warm(100)) == pytest.approx(0.4, rel=1e-5)


def test_moment_dtype_rejects_garbage():
    with pytest.raises(ValueError, match="moment_dtype"):
        make_optimizer(OptimizerConfig(name="adam",
                                       moment_dtype="float16x"))


def test_total_num_replicas_mismatch_raises():
    m = get_model("mlp", TrainConfig(model="mlp"))
    mesh = local_mesh(2, {"data": 2})
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
    with pytest.raises(ValueError, match="backup"):
        SyncReplicas(m.loss, tx, mesh,
                     sync=SyncConfig(total_num_replicas=4))


def test_total_num_replicas_match_ok():
    m = get_model("mlp", TrainConfig(model="mlp"))
    mesh = local_mesh(2, {"data": 2})
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
    SyncReplicas(m.loss, tx, mesh,
                 sync=SyncConfig(total_num_replicas=2,
                                 replicas_to_aggregate=2))


@pytest.mark.parametrize("name", ["mlp", "pipe_mlp", "lenet", "resnet20",
                                  "resnet50", "bert_tiny", "moe_bert_tiny"])
def test_compute_dtype_bf16_traces_and_logits_f32(name):
    """dtype=bfloat16 must trace end to end (regression: the bf16 dense
    output once broke pipe_mlp's scan-carry dtype) and classification /
    MLM logits must come out f32 for softmax-loss headroom."""
    cfg = TrainConfig(model=name, dtype="bfloat16")
    m = get_model(name, cfg)
    out = m.init(jax.random.key(0))
    params, extras = out if isinstance(out, tuple) else (out, {})
    batch = m.dummy_batch(8)

    logits_shape = jax.eval_shape(
        lambda p, e, b: m.apply(p, e, b, train=False)[0],
        params, extras, batch)
    assert logits_shape.dtype == jnp.float32, logits_shape.dtype

    loss_shape = jax.eval_shape(
        lambda p, e, b: m.loss(p, e, b, jax.random.key(1))[0],
        params, extras, batch)
    assert loss_shape.dtype == jnp.float32


def test_polynomial_schedule():
    """tf.train.polynomial_decay parity: (lr0-end)*(1-t/T)^p + end, then
    hold at end_learning_rate."""
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_schedule)
    sched = make_schedule(OptimizerConfig(
        learning_rate=1.0, decay_schedule="polynomial",
        decay_steps=100, end_learning_rate=0.1, decay_power=2.0))
    assert float(sched(0)) == pytest.approx(1.0)
    # (1.0-0.1)*(1-0.5)^2 + 0.1
    assert float(sched(50)) == pytest.approx(0.9 * 0.25 + 0.1, rel=1e-5)
    assert float(sched(100)) == pytest.approx(0.1)
    assert float(sched(500)) == pytest.approx(0.1)      # holds at floor


def test_polynomial_schedule_bert_recipe():
    """power=1.0 + warmup is the original BERT recipe
    (bert/optimization.py): linear ramp to base over warmup_steps while
    the polynomial decays from step 0 — so post-warmup LR is the
    UN-rebased tf.train.polynomial_decay value base*(1 - t/T), including
    the recipe's documented step-down right after warmup ends."""
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_schedule)
    sched = make_schedule(OptimizerConfig(
        learning_rate=1e-4, decay_schedule="polynomial",
        total_steps=1000, warmup_steps=100))
    assert float(sched(50)) == pytest.approx(0.5e-4, rel=1e-5)   # mid-warmup
    assert float(sched(100)) == pytest.approx(0.9e-4, rel=1e-5)  # 1 - 100/1000
    assert float(sched(550)) == pytest.approx(0.45e-4, rel=1e-5)  # 1 - 550/1000
    assert float(sched(1000)) == pytest.approx(0.0, abs=1e-12)
    with pytest.raises(ValueError, match="polynomial"):
        make_schedule(OptimizerConfig(decay_schedule="polynomial",
                                      total_steps=50, warmup_steps=50))


def test_lars_lamb_reject_bf16_moments():
    """optax.lars/lamb expose no accumulator dtype: the flag must hard
    error rather than silently no-op."""
    for name in ("lars", "lamb"):
        with pytest.raises(ValueError, match="moment_dtype"):
            make_optimizer(OptimizerConfig(name=name,
                                           moment_dtype="bfloat16"))


@pytest.mark.parametrize("opt", ["lars", "lamb"])
def test_large_batch_optimizer_trains(opt):
    """lars/lamb run end to end under SyncReplicas and the loss drops
    (the large-batch recipes the sync-DP scaling story pairs with)."""
    m = get_model("mlp", TrainConfig(model="mlp"))
    mesh = local_mesh(1, {"data": 1})
    tx = make_optimizer(OptimizerConfig(name=opt, learning_rate=0.05,
                                        weight_decay=1e-4))
    sync = SyncReplicas(m.loss, tx, mesh)
    state = sync.init(m.init)
    batch = m.dummy_batch(64)
    losses = []
    for _ in range(8):
        state, metrics = sync.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


def test_lars_trust_ratio_scale_invariance():
    """The LARS property: the update direction is normalized per layer
    (||update|| ~ trust_coefficient * ||param||), so scaling the
    gradient by 100x leaves the update norm unchanged — unlike sgd."""
    import optax

    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)
    params = {"kernel": jnp.ones((8, 8))}
    g1 = {"kernel": jnp.full((8, 8), 0.01)}
    g2 = {"kernel": jnp.full((8, 8), 1.0)}
    tx = make_optimizer(OptimizerConfig(name="lars", learning_rate=1.0,
                                        momentum=0.0))
    u1, _ = tx.update(g1, tx.init(params), params)
    u2, _ = tx.update(g2, tx.init(params), params)
    n1 = float(optax.global_norm(u1))
    n2 = float(optax.global_norm(u2))
    assert n1 == pytest.approx(n2, rel=1e-5)
    assert n1 > 0


def test_lamb_bias_excluded_from_decay_by_default():
    """wd_mask=exclude_1d reaches lamb's decay mask: with zero grads the
    adam term is 0, so only decayed leaves move."""
    import optax

    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)
    params = {"kernel": jnp.ones((4, 4)), "bias": jnp.ones((4,))}
    grads = jax.tree_util.tree_map(jnp.zeros_like, params)
    tx = make_optimizer(OptimizerConfig(name="lamb", learning_rate=1.0,
                                        weight_decay=0.1))
    updates, _ = tx.update(grads, tx.init(params), params)
    new = optax.apply_updates(params, updates)
    assert float(jnp.max(jnp.abs(new["kernel"] - 1.0))) > 0   # decayed
    np.testing.assert_array_equal(np.asarray(new["bias"]), np.ones(4))


def test_adafactor_trains_and_factored_state_is_small():
    """adafactor runs under SyncReplicas (loss drops) and, with
    momentum=0, its optimizer state is a small fraction of param size —
    the factored-second-moment memory claim (row+col vectors instead of
    a full matrix per weight)."""
    m = get_model("mlp", TrainConfig(model="mlp"))
    mesh = local_mesh(1, {"data": 1})
    tx = make_optimizer(OptimizerConfig(name="adafactor",
                                        learning_rate=0.01,
                                        momentum=0.0))
    sync = SyncReplicas(m.loss, tx, mesh)
    state = sync.init(m.init)
    batch = m.dummy_batch(64)
    losses = []
    for _ in range(8):
        state, metrics = sync.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses

    # the factored-memory claim, on a matrix big enough to factor
    # (optax only factors dims >= min_dim_size_to_factor=128): full 2nd
    # moments would be 512*256 floats; factored is 512+256 per matrix
    big = {"k": jnp.ones((512, 256))}
    tx2 = make_optimizer(OptimizerConfig(name="adafactor",
                                         momentum=0.0))
    n_opt = sum(int(np.size(p)) for p in
                jax.tree_util.tree_leaves(tx2.init(big))
                if hasattr(p, "size"))
    assert n_opt < 0.05 * 512 * 256, n_opt


def test_adafactor_momentum_knob_is_load_bearing():
    """--momentum > 0 adds a momentum accumulator (state grows to
    ~params size); the knob must not be silently ignored."""
    params = {"k": jnp.ones((64, 32))}
    tx0 = make_optimizer(OptimizerConfig(name="adafactor", momentum=0.0))
    tx9 = make_optimizer(OptimizerConfig(name="adafactor", momentum=0.9))
    n0 = sum(int(np.size(p)) for p in
             jax.tree_util.tree_leaves(tx0.init(params))
             if hasattr(p, "size"))
    n9 = sum(int(np.size(p)) for p in
             jax.tree_util.tree_leaves(tx9.init(params))
             if hasattr(p, "size"))
    assert n9 >= n0 + 64 * 32, (n0, n9)


def test_adafactor_composes_with_tensor_parallel_rules():
    """Factored state (rank-1 v_row/v_col under param paths) must not
    inherit rank-2 kernel PartitionSpecs — it replicates instead of
    failing placement (state_shardings rank guard)."""
    cfg = TrainConfig(model="bert_tiny")
    m = get_model("bert_tiny", cfg)
    mesh = local_mesh(2, {"model": 2})
    from distributed_tensorflow_example_tpu.config import MeshShape
    tx = make_optimizer(OptimizerConfig(name="adafactor",
                                        learning_rate=1e-3,
                                        momentum=0.0))
    sync = SyncReplicas(m.loss, tx, mesh,
                        rules=m.sharding_rules(MeshShape(model=2)))
    state = sync.init(m.init)
    state, metrics = sync.step(state, sync.shard_batch(m.dummy_batch(8)))
    assert np.isfinite(float(metrics["loss"]))


def test_natural_exp_and_inverse_time_schedules():
    """tf.train.natural_exp_decay / inverse_time_decay parity at
    absolute steps, continuous (staircase off)."""
    import math as _math

    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_schedule)
    ne = make_schedule(OptimizerConfig(
        learning_rate=0.8, decay_schedule="natural_exp",
        decay_steps=100, decay_factor=0.5))
    assert float(ne(0)) == pytest.approx(0.8)
    assert float(ne(100)) == pytest.approx(0.8 * _math.exp(-0.5),
                                           rel=1e-5)
    assert float(ne(200)) == pytest.approx(0.8 * _math.exp(-1.0),
                                           rel=1e-5)
    # absolute-step contract under warmup
    ne_w = make_schedule(OptimizerConfig(
        learning_rate=0.8, decay_schedule="natural_exp",
        decay_steps=100, decay_factor=0.5, warmup_steps=100))
    assert float(ne_w(200)) == pytest.approx(0.8 * _math.exp(-1.0),
                                             rel=1e-5)

    it = make_schedule(OptimizerConfig(
        learning_rate=0.8, decay_schedule="inverse_time",
        decay_steps=100, decay_factor=0.5))
    assert float(it(0)) == pytest.approx(0.8)
    assert float(it(100)) == pytest.approx(0.8 / 1.5, rel=1e-5)
    assert float(it(400)) == pytest.approx(0.8 / 3.0, rel=1e-5)
    it_w = make_schedule(OptimizerConfig(
        learning_rate=0.8, decay_schedule="inverse_time",
        decay_steps=100, decay_factor=0.5, warmup_steps=100))
    assert float(it_w(400)) == pytest.approx(0.8 / 3.0, rel=1e-5)
    for name in ("natural_exp", "inverse_time"):
        with pytest.raises(ValueError, match="decay_steps"):
            make_schedule(OptimizerConfig(decay_schedule=name))


def test_grad_clip_value():
    """tf.clip_by_value on gradients: elements exceed the bound, the
    update magnitude is capped per element (sgd lr=1 isolates it)."""
    import optax
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=1.0,
                                        grad_clip_value=0.5))
    params = {"w": jnp.zeros((3,))}
    grads = {"w": jnp.asarray([0.2, -3.0, 10.0])}
    updates, _ = tx.update(grads, tx.init(params), params)
    np.testing.assert_allclose(np.asarray(updates["w"]),
                               [-0.2, 0.5, -0.5], rtol=1e-6)


def test_cosine_floor_via_end_learning_rate():
    """tf.train.cosine_decay's alpha floor: the schedule decays to
    end_learning_rate, not to zero, and holds there."""
    import math as _math

    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_schedule)
    sched = make_schedule(OptimizerConfig(
        learning_rate=0.4, decay_schedule="cosine", total_steps=100,
        end_learning_rate=0.04))
    assert float(sched(0)) == pytest.approx(0.4)
    # halfway: floor + (base-floor) * 0.5*(1+cos(pi/2)) = midpoint
    mid = 0.04 + (0.4 - 0.04) * 0.5 * (1 + _math.cos(_math.pi / 2))
    assert float(sched(50)) == pytest.approx(mid, rel=1e-5)
    assert float(sched(100)) == pytest.approx(0.04, rel=1e-5)
    assert float(sched(500)) == pytest.approx(0.04, rel=1e-5)
    # default stays decay-to-zero
    plain = make_schedule(OptimizerConfig(
        learning_rate=0.4, decay_schedule="cosine", total_steps=100))
    assert float(plain(100)) == pytest.approx(0.0, abs=1e-9)


def test_cosine_and_linear_end_at_absolute_total_steps():
    """Under warmup, cosine/linear decays span end-of-warmup to the
    ABSOLUTE total_steps endpoint (the standard ramp-then-decay recipe),
    not total_steps + warmup."""
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_schedule)
    cos = make_schedule(OptimizerConfig(
        learning_rate=0.4, decay_schedule="cosine", total_steps=100,
        warmup_steps=20, end_learning_rate=0.04))
    assert float(cos(20)) == pytest.approx(0.4, rel=1e-5)   # peak
    assert float(cos(100)) == pytest.approx(0.04, rel=1e-5)  # floor AT 100
    lin = make_schedule(OptimizerConfig(
        learning_rate=0.4, decay_schedule="linear", total_steps=100,
        warmup_steps=20))
    assert float(lin(100)) == pytest.approx(0.0, abs=1e-9)
    assert float(lin(60)) == pytest.approx(0.2, rel=1e-5)   # midpoint


def test_max_inflight_steps_bounds_the_dispatch_queue(cpu8, monkeypatch):
    """max_inflight_steps=N blocks the host every N trained steps (the
    documented mitigation for runtimes that misbehave under deep
    dispatch queues); 0 never blocks mid-loop; negative is a hard
    error. Counted by intercepting jax.block_until_ready."""
    from distributed_tensorflow_example_tpu.config import (DataConfig,
                                                           MeshShape)
    from distributed_tensorflow_example_tpu.data.mnist import synthetic_mnist
    from distributed_tensorflow_example_tpu.train.trainer import Trainer

    data = synthetic_mnist(num_train=256, num_test=32, seed=0)

    def run(max_inflight, steps=6):
        cfg = TrainConfig(
            model="mlp", train_steps=steps, mesh=MeshShape(data=4),
            max_inflight_steps=max_inflight,
            data=DataConfig(batch_size=32, seed=1),
            optimizer=OptimizerConfig(name="sgd", learning_rate=0.1))
        model = get_model("mlp", cfg)
        t = Trainer(model, cfg, {"x": data["train_x"],
                                 "y": data["train_y"]},
                    mesh=local_mesh(4), process_index=0, num_processes=1)
        calls = []
        real = jax.block_until_ready
        monkeypatch.setattr(jax, "block_until_ready",
                            lambda x: (calls.append(1), real(x))[1])
        with t:
            t.train()
        monkeypatch.setattr(jax, "block_until_ready", real)
        return len(calls)

    free = run(0)          # blocks only at loop exit (+ eval-free end)
    every2 = run(2)        # + one block per 2 trained steps
    every1 = run(1)
    assert every2 >= free + 3, (free, every2)
    assert every1 >= free + 6, (free, every1)
    with pytest.raises(ValueError, match="max_inflight_steps"):
        run(-1)
