"""C++ native loader: availability, parser parity, batch-sequence parity."""

import os
import struct

import numpy as np
import pytest

from distributed_tensorflow_example_tpu.data import native
from distributed_tensorflow_example_tpu.data import mnist as py_mnist
from distributed_tensorflow_example_tpu.data import cifar as py_cifar
from distributed_tensorflow_example_tpu.data.loader import (ShardedLoader,
                                                            make_loader)

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason="native loader not built (g++/make unavailable)")


def test_abi_and_availability():
    assert native.available()


def _write_idx(tmp_path):
    n, r, c = 9, 5, 5
    rs = np.random.RandomState(3)
    imgs = rs.randint(0, 256, size=(n, r, c)).astype(np.uint8)
    lbls = (np.arange(n) % 10).astype(np.uint8)
    ip = os.path.join(tmp_path, "imgs")
    lp = os.path.join(tmp_path, "lbls")
    with open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, r, c) + imgs.tobytes())
    with open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, n) + lbls.tobytes())
    return ip, lp, imgs, lbls


def test_idx_parser_matches_python(tmp_path):
    ip, lp, imgs, lbls = _write_idx(str(tmp_path))
    np.testing.assert_array_equal(native.read_idx_images(ip),
                                  py_mnist.read_idx_images(ip))
    np.testing.assert_array_equal(native.read_idx_labels(lp),
                                  py_mnist.read_idx_labels(lp))
    np.testing.assert_array_equal(native.read_idx_images(ip), imgs)


def test_idx_parser_bad_magic(tmp_path):
    p = str(tmp_path / "bad")
    with open(p, "wb") as f:
        f.write(struct.pack(">IIII", 7, 1, 1, 1) + b"\0")
    with pytest.raises(ValueError):
        native.read_idx_images(p)


def test_cifar_parser_matches_python(tmp_path):
    rs = np.random.RandomState(0)
    recs = []
    for _ in range(6):
        recs.append(np.concatenate([
            [rs.randint(0, 10)],
            rs.randint(0, 256, size=3072)]).astype(np.uint8))
    p = str(tmp_path / "batch.bin")
    np.concatenate(recs).tofile(p)
    nx, ny = native.read_cifar_bin(p)
    px, py = py_cifar.read_cifar_bin(p)
    np.testing.assert_allclose(nx, px)
    np.testing.assert_array_equal(ny, py)


def _arrays(n=64, d=7):
    rs = np.random.RandomState(1)
    return {"x": rs.rand(n, d).astype(np.float32),
            "y": rs.randint(0, 10, size=n).astype(np.int32)}


def test_native_loader_matches_python_loader():
    """Bit-identical batch sequences across two epochs."""
    a = _arrays()
    py_it = iter(ShardedLoader(a, 16, seed=5))
    nat_it = iter(native.NativeLoader(a, 16, seed=5))
    for _ in range(10):                      # 4 steps/epoch → crosses epochs
        pb = next(py_it)
        nb = next(nat_it)
        np.testing.assert_array_equal(pb["x"], nb["x"])
        np.testing.assert_array_equal(pb["y"], nb["y"])


def test_native_loader_process_sharding():
    a = _arrays()
    whole = iter(ShardedLoader(a, 16, seed=2))
    parts = [iter(native.NativeLoader(a, 16, seed=2, process_index=i,
                                      num_processes=4)) for i in range(4)]
    for _ in range(4):
        gb = next(whole)
        cat = np.concatenate([next(p)["x"] for p in parts])
        np.testing.assert_array_equal(gb["x"], cat)


def test_native_loader_no_shuffle_order():
    a = _arrays(n=32)
    it = iter(native.NativeLoader(a, 8, shuffle=False))
    b0 = next(it)
    np.testing.assert_array_equal(b0["x"], a["x"][:8])


def test_make_loader_native_path_and_fallback():
    a = _arrays()
    it = make_loader(a, 16, native=True, seed=0)
    from distributed_tensorflow_example_tpu.data.native import NativeLoader
    b = next(it)
    assert b["x"].shape == (16, 7)
    # >2 arrays → silently uses the python path
    a3 = dict(a, z=np.zeros(64, np.int32))
    it2 = make_loader(a3, 16, native=True, seed=0)
    assert next(it2)["z"].shape == (16,)


def test_native_loader_rejects_bad_layout():
    with pytest.raises(ValueError, match="empty"):
        native.NativeLoader({}, 4)
    with pytest.raises(ValueError, match="length mismatch"):
        native.NativeLoader({"x": np.zeros((8, 2)), "y": np.zeros(6)}, 4)
    with pytest.raises(ValueError):
        native.NativeLoader(_arrays(), 15, num_processes=4)


def test_native_loader_close_idempotent():
    l = native.NativeLoader(_arrays(), 16)
    it = iter(l)
    next(it)
    l.close()
    l.close()


def test_native_loader_six_key_bert_batch():
    """The flagship BERT batch layout (6 arrays, mixed dtypes/ranks) rides
    the C++ path bit-identically to the Python loader (VERDICT r1
    missing #5: the old ABI hard-limited native to 2-array layouts)."""
    n, s, p = 48, 16, 4
    rs = np.random.RandomState(7)
    a = {
        "input_ids": rs.randint(0, 1000, size=(n, s)).astype(np.int32),
        "attention_mask": np.ones((n, s), np.int32),
        "token_type_ids": np.zeros((n, s), np.int32),
        "mlm_positions": rs.randint(0, s, size=(n, p)).astype(np.int32),
        "mlm_labels": rs.randint(0, 1000, size=(n, p)).astype(np.int32),
        "mlm_weights": rs.rand(n, p).astype(np.float32),
    }
    py_it = iter(ShardedLoader(a, 16, seed=11))
    nat = native.NativeLoader(a, 16, seed=11)
    nat_it = iter(nat)
    for _ in range(2 * (n // 16)):        # two epochs
        pb, nb = next(py_it), next(nat_it)
        assert sorted(pb) == sorted(nb)
        for k in pb:
            np.testing.assert_array_equal(pb[k], nb[k], err_msg=k)
    nat.close()


def test_native_loader_multiprocess_shards_six_keys():
    a = {
        "input_ids": np.arange(64 * 4, dtype=np.int32).reshape(64, 4),
        "mask": np.ones((64, 4), np.int32),
        "labels": np.arange(64, dtype=np.int32),
    }
    outs = []
    for pi in range(2):
        it = iter(native.NativeLoader(a, 32, process_index=pi,
                                      num_processes=2, seed=3))
        outs.append(next(it))
    # the two process shards partition the first global batch
    ids = np.concatenate([o["labels"] for o in outs])
    assert len(set(ids.tolist())) == 32
