"""Block-paged KV-cache pool + shared-prefix reuse (round 10):

- paged decode attention (XLA gather path BITWISE vs the slab
  reference, Pallas scalar-prefetch kernel vs the gather path),
- paged model methods (decode step bitwise vs slab on equal logical
  contents; paged prefill vs the monolithic oracle),
- BlockPool / PrefixCache / RetryAfterEstimator units (refcounts,
  exhaustion, fragmentation, LRU eviction, EMA math),
- GenerationEngine on paged artifacts: cold/greedy parity vs the
  single-request oracle, exact-hit and divergent-suffix prefix reuse
  with ZERO prefill dispatches, copy-on-write on divergence,
  mid-decode block exhaustion failing ONE request loudly while
  neighbors finish, >= 2x admitted concurrency vs the slab slot count
  at equal pool bytes, and block-level /stats.
"""

import json
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import TrainConfig
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.ops.pallas.decode_attention import (
    decode_attention, paged_decode_attention, paged_tile_friendly)
from distributed_tensorflow_example_tpu.serving import (export_generator,
                                                        load_stepwise)
from distributed_tensorflow_example_tpu.serving_batch import (
    BlockPool, BlocksExhaustedError, GenerationEngine, PrefixCache,
    RetryAfterEstimator)
from distributed_tensorflow_example_tpu.serving_http import PredictServer

PROMPT_LEN = 8
MAX_NEW = 5
SLOTS = 4
BLOCK = 4


# ---------------------------------------------------------------------------
# kernel / op level
# ---------------------------------------------------------------------------

def _rand_pool(rs, n, bs, h, d):
    return (rs.randn(n, bs, h, d).astype(np.float32),
            rs.randn(n, bs, h, d).astype(np.float32))


def test_paged_xla_gather_bitwise_matches_slab_reference():
    """Equal logical contents -> the gather path IS the slab path,
    bit for bit (the paged byte-parity foundation)."""
    rs = np.random.RandomState(0)
    b, h, d, bs, nb = 3, 4, 32, 4, 3
    n = 1 + b * nb
    kp, vp = _rand_pool(rs, n, bs, h, d)
    q = rs.randn(b, h, d).astype(np.float32)
    bt = rs.permutation(np.arange(1, n))[:b * nb].reshape(b, nb)
    bt = bt.astype(np.int32)
    pos = np.array([2, 7, 11], np.int32)
    pad = np.array([0, 1, 0], np.int32)
    ks = kp[bt].reshape(b, nb * bs, h, d)
    vs = vp[bt].reshape(b, nb * bs, h, d)
    want = decode_attention(jnp.asarray(q), jnp.asarray(ks),
                            jnp.asarray(vs), pos=jnp.asarray(pos),
                            pad=jnp.asarray(pad), impl="xla")
    got = paged_decode_attention(jnp.asarray(q), jnp.asarray(kp),
                                 jnp.asarray(vp), block_tables=bt,
                                 pos=pos, pad=pad, impl="xla")
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_paged_kernel_matches_gather_reference():
    """The scalar-prefetch kernel (interpret mode off-TPU) against the
    gather reference at a tile-friendly shape, including a row whose
    table holds null/stale entries past its pos."""
    rs = np.random.RandomState(1)
    b, h, d, bs, nb = 2, 2, 64, 128, 3
    assert paged_tile_friendly(bs, d)
    n = 1 + b * nb
    q = rs.randn(b, h, d).astype(np.float32)
    kp, vp = _rand_pool(rs, n, bs, h, d)
    bt = np.arange(1, 1 + b * nb, dtype=np.int32).reshape(b, nb)
    bt[0, 2] = 0                    # beyond pos: never read
    pos = np.array([130, 380], np.int32)
    pad = np.array([3, 0], np.int32)
    want = paged_decode_attention(jnp.asarray(q), jnp.asarray(kp),
                                  jnp.asarray(vp), block_tables=bt,
                                  pos=pos, pad=pad, impl="xla")
    got = paged_decode_attention(jnp.asarray(q), jnp.asarray(kp),
                                 jnp.asarray(vp), block_tables=bt,
                                 pos=pos, pad=pad, impl="pallas")
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=2e-6, atol=2e-6)


def test_paged_kernel_matches_gather_on_verify_expanded_rows():
    """The speculative verify path (round 16) presents paged attention
    with ROW-EXPANDED queries: lanes (b, j) sit at consecutive
    positions pos_b + j and SHARE row b's block table. Both impls must
    agree on exactly that shape — the kernel's scalar-prefetch index
    maps see repeated table rows and per-lane pos, the gather
    reference sees them as ordinary independent rows."""
    rs = np.random.RandomState(2)
    b, kk, h, d, bs, nb = 2, 4, 2, 64, 128, 3
    assert paged_tile_friendly(bs, d)
    n = 1 + b * nb
    kp, vp = _rand_pool(rs, n, bs, h, d)
    q = rs.randn(b * kk, h, d).astype(np.float32)
    bt = np.arange(1, 1 + b * nb, dtype=np.int32).reshape(b, nb)
    bt_e = np.repeat(bt, kk, axis=0)
    pos = (np.array([[100], [250]], np.int32)
           + np.arange(kk, dtype=np.int32)[None]).reshape(-1)
    pad = np.repeat(np.array([3, 0], np.int32), kk)
    want = paged_decode_attention(jnp.asarray(q), jnp.asarray(kp),
                                  jnp.asarray(vp), block_tables=bt_e,
                                  pos=pos, pad=pad, impl="xla")
    got = paged_decode_attention(jnp.asarray(q), jnp.asarray(kp),
                                 jnp.asarray(vp), block_tables=bt_e,
                                 pos=pos, pad=pad, impl="pallas")
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=2e-6, atol=2e-6)
    # the expanded call is ALSO exactly the per-lane single-query call
    # — lane independence is what the verify program's exactness rides
    for b_i in range(b):
        for j in range(kk):
            r = b_i * kk + j
            one = paged_decode_attention(
                jnp.asarray(q[r:r + 1]), jnp.asarray(kp),
                jnp.asarray(vp), block_tables=bt[b_i:b_i + 1],
                pos=pos[r:r + 1], pad=pad[r:r + 1], impl="xla")
            np.testing.assert_array_equal(np.asarray(want[r]),
                                          np.asarray(one[0]))


def test_paged_kernel_rejects_unfriendly_shapes():
    q = jnp.zeros((1, 2, 32))
    kp = jnp.zeros((2, 4, 2, 32))
    with pytest.raises(ValueError, match="block_size"):
        paged_decode_attention(q, kp, kp, block_tables=np.zeros(
            (1, 1), np.int32), pos=np.zeros(1, np.int32),
            pad=np.zeros(1, np.int32), impl="pallas")


@pytest.fixture(scope="module")
def tiny_model():
    m = get_model("gpt_tiny", TrainConfig(model="gpt_tiny"))
    return m, m.init(jax.random.key(0))


def test_paged_decode_step_bitwise_matches_slab(tiny_model):
    """decode_step_batched_paged == decode_step_batched bit for bit on
    equal logical contents — logits AND the written cache bytes."""
    m, params = tiny_model
    c = m.cfg
    rs = np.random.RandomState(2)
    b, bs, nb = 3, 4, 3
    t = nb * bs
    l, h, d = c.layers, c.heads, m.head_dim
    n = 1 + b * nb
    slab = {x: rs.randn(l, b, t, h, d).astype(np.float32)
            for x in ("k", "v")}
    bt = (1 + np.arange(b * nb).reshape(b, nb)).astype(np.int32)
    pools = {}
    for x in ("k", "v"):
        pool = np.zeros((l, n, bs, h, d), np.float32)
        for bb in range(b):
            for j in range(nb):
                pool[:, bt[bb, j]] = slab[x][:, bb, j * bs:(j + 1) * bs]
        pools[x] = jnp.asarray(pool)
    slabj = {x: jnp.asarray(v) for x, v in slab.items()}
    stacked = m.stack_decode_params(params)
    tok = jnp.asarray(rs.randint(0, c.vocab_size, (b,)), jnp.int32)
    pos = jnp.asarray([3, 7, 11], jnp.int32)
    pad = jnp.zeros((b,), jnp.int32)
    alive = jnp.asarray([1, 1, 0], jnp.int32)
    lg_s, new_s = m.decode_step_batched(params, stacked, slabj, tok,
                                        pos, pad, alive,
                                        decode_attention="xla")
    lg_p, new_p = m.decode_step_batched_paged(params, stacked, pools,
                                              bt, tok, pos, pad, alive,
                                              decode_attention="xla")
    np.testing.assert_array_equal(np.asarray(lg_s), np.asarray(lg_p))
    for x in ("k", "v"):
        gathered = np.asarray(new_p[x])[:, bt].reshape(l, b, t, h, d)
        np.testing.assert_array_equal(gathered, np.asarray(new_s[x]))


def test_paged_prefill_matches_oracle_and_writes_blocks(tiny_model):
    """paged_prefill's first-token pick equals the monolithic ragged
    oracle's, and the written blocks hold the left-aligned prefill
    K/V."""
    m, params = tiny_model
    c = m.cfg
    l, h, d = c.layers, c.heads, m.head_dim
    rs = np.random.RandomState(3)
    p = 6
    prompt = rs.randint(0, c.vocab_size, (p,)).astype(np.int32)
    ids = np.zeros((1, PROMPT_LEN), np.int32)
    mask = np.zeros((1, PROMPT_LEN), np.int32)
    ids[0, :p] = prompt
    mask[0, :p] = 1
    tr = np.array([2, 4], np.int32)
    kp = jnp.zeros((l, 6, BLOCK, h, d), jnp.float32)
    vp = jnp.zeros((l, 6, BLOCK, h, d), jnp.float32)
    logits, kp2, vp2 = m.paged_prefill(params, jnp.asarray(ids),
                                       jnp.asarray(mask), kp, vp,
                                       jnp.asarray(tr))
    last_h, _, _ = m.ragged_prefill(params, jnp.asarray(ids),
                                    jnp.asarray(mask), PROMPT_LEN)
    want = m.lm_logits(params, last_h[:, None])[:, 0]
    assert int(jnp.argmax(logits[0])) == int(jnp.argmax(want[0]))
    # written blocks = the left-aligned prefill's own K/V
    hfull, caches = m._prefill_full(
        params, jnp.asarray(np.where(mask, ids, 0)), 2 * BLOCK,
        mask=jnp.asarray(mask),
        pos_ids=jnp.arange(PROMPT_LEN, dtype=jnp.int32)[None])
    kv = m._stack_caches(caches)
    for x, pool in (("k", kp2), ("v", vp2)):
        want_blocks = np.asarray(kv[x])[:, 0].reshape(l, 2, BLOCK, h, d)
        np.testing.assert_array_equal(np.asarray(pool)[:, tr],
                                      want_blocks)


# ---------------------------------------------------------------------------
# allocator / cache / estimator units
# ---------------------------------------------------------------------------

def test_block_pool_alloc_release_refcount():
    bp = BlockPool(6)                       # 5 usable + null
    assert bp.usable == 5 and bp.free_count == 5
    run = bp.alloc(3)
    assert len(set(run)) == 3 and 0 not in run
    assert bp.free_count == 2
    bp.retain(run[:1])                      # shared with a second owner
    bp.release(run)
    # the shared block survives its first release...
    assert bp.free_count == 4
    assert bp.refcount(run[0]) == 1
    bp.release(run[:1])                     # ...and frees at the LAST
    assert bp.free_count == 5


def test_block_pool_exhaustion_is_all_or_nothing():
    bp = BlockPool(4)
    bp.alloc(2)
    with pytest.raises(BlocksExhaustedError):
        bp.alloc(2)
    assert bp.free_count == 1               # nothing partially taken


def test_block_pool_fragmentation_after_mixed_retirement():
    """Release a non-contiguous subset; the next alloc serves from the
    holes — physical contiguity is irrelevant under table
    indirection."""
    bp = BlockPool(9)
    run = bp.alloc(8)
    odd = run[1::2]
    bp.release(odd)
    assert bp.free_count == 4
    again = bp.alloc(4)
    assert sorted(again) == sorted(odd)
    assert bp.free_count == 0
    with pytest.raises(BlocksExhaustedError):
        bp.alloc(1)


def test_block_pool_double_release_raises():
    bp = BlockPool(3)
    run = bp.alloc(1)
    bp.release(run)
    with pytest.raises(AssertionError, match="double release"):
        bp.release(run)
    with pytest.raises(AssertionError, match="retain of free"):
        bp.retain(run)


def test_prefix_cache_longest_match_and_lru_eviction():
    bp = BlockPool(10)
    pc = PrefixCache(bp, block_size=4)
    toks = np.arange(100, 110, dtype=np.int32)      # 10 tokens
    run = bp.alloc(3)                               # ceil(10/4)
    pc.insert(toks, run)
    # entries: 4-token chain, 8-token chain, exact 10-token
    assert len(pc) == 3
    n, blocks = pc.lookup(toks)                     # exact wins
    assert n == 10 and list(blocks) == run
    n, blocks = pc.lookup(np.concatenate([toks[:7], [999]]).astype(np.int32))
    assert n == 4 and list(blocks) == run[:1]       # longest chain
    n, _ = pc.lookup(np.array([1, 2, 3], np.int32))
    assert n == 0
    assert pc.hits == 2 and pc.misses == 1
    # record=False probes (the engine's block-pressure deferral loop)
    # leave the counters alone — one admission counts exactly once
    pc.lookup(toks, record=False)
    pc.lookup(np.array([1, 2, 3], np.int32), record=False)
    assert pc.hits == 2 and pc.misses == 1
    # eviction: release the owner's refs, then evict — blocks free
    # only when the LAST reference (the cache's) is dropped
    bp.release(run)
    assert bp.free_count == 6                       # cache still holds
    pc.evict(9)
    assert bp.free_count == 9 and len(pc) == 0


def test_retry_after_estimator_ema_math():
    est = RetryAfterEstimator(alpha=0.5)
    assert est.estimate(10) == 1.0                  # no signal yet
    est.observe(0.10)
    assert est.ema_step_s == pytest.approx(0.10)
    est.observe(0.20)
    assert est.ema_step_s == pytest.approx(0.15)
    est.observe(0.05)
    assert est.ema_step_s == pytest.approx(0.10)
    # steps-to-free and queue waves scale the estimate
    assert est.estimate(4) == pytest.approx(0.4)
    assert est.estimate(4, queue_ahead=8, slots=4) \
        == pytest.approx(0.4 * 3)
    assert est.estimate(0.1) == pytest.approx(0.1)  # floor


# ---------------------------------------------------------------------------
# engine level (paged artifacts)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def paged_dir(tmp_path_factory, tiny_model):
    """One roomy paged export shared module-wide (48 blocks, so prefix
    entries never get evicted mid-test)."""
    d = str(tmp_path_factory.mktemp("paged"))
    m, params = tiny_model
    export_generator(m, params, d, prompt_len=PROMPT_LEN,
                     max_new_tokens=MAX_NEW, batch_size=1, ragged=True,
                     stepwise=True, slots=SLOTS, paged=True,
                     block_size=BLOCK, num_blocks=48,
                     platforms=("cpu",))
    return d


def _oracle(m, params, prompt, max_new=MAX_NEW, **kw):
    ids = np.zeros((1, PROMPT_LEN), np.int32)
    mask = np.zeros((1, PROMPT_LEN), np.int32)
    ids[0, :prompt.size] = prompt
    mask[0, :prompt.size] = 1
    return np.asarray(m.generate(params, jnp.asarray(ids), max_new,
                                 prompt_mask=jnp.asarray(mask),
                                 **kw))[0].tolist()


def _prompts(n, seed=0, lo=1, hi=PROMPT_LEN):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 1000, (int(rs.randint(lo, hi + 1)),)
                       ).astype(np.int32) for _ in range(n)]


def _drain(eng):
    """Drive the engine synchronously (no scheduler thread): admission
    + shared steps until idle — deterministic order for the allocator
    tests."""
    for _ in range(10_000):
        eng._admit()
        if not eng._live:
            if not eng._queue:
                return
            continue
        eng._shared_step()
    raise AssertionError("engine did not drain")


def test_paged_cold_greedy_parity(paged_dir, tiny_model):
    """Cold paged serving is byte-identical to the single-request
    oracle for a full mixed-length concurrent wave."""
    m, params = tiny_model
    prompts = _prompts(SLOTS * 2, seed=10)
    eng = GenerationEngine(load_stepwise(paged_dir))
    assert eng.paged
    futs = [eng.submit(p) for p in prompts]
    eng.start()
    try:
        got = [f.result(timeout=120) for f in futs]
    finally:
        eng.close()
    for p, g in zip(prompts, got):
        assert g == _oracle(m, params, p)


def test_exact_prefix_hit_skips_prefill_and_keeps_parity(paged_dir,
                                                         tiny_model):
    """Resubmitting known prompts costs ZERO prefill dispatches (the
    headline claim) and stays byte-identical — including the
    copy-on-write protecting the cached tail block."""
    m, params = tiny_model
    prompts = _prompts(4, seed=11)
    eng = GenerationEngine(load_stepwise(paged_dir))
    futs = [eng.submit(p) for p in prompts]
    eng.start()
    try:
        first = [f.result(timeout=120) for f in futs]
        pre = eng.prefills
        second = [eng.submit(p).result(timeout=120) for p in prompts]
        third = [eng.submit(p).result(timeout=120) for p in prompts]
    finally:
        eng.close()
    assert eng.prefills == pre, "repeat prompts must not prefill"
    for p, a, b, c in zip(prompts, first, second, third):
        want = _oracle(m, params, p)
        assert a == want and b == want and c == want
    s = eng.stats()
    assert s["prefix_cache_hits"] >= 8
    assert s["prefill_tokens_saved"] > 0


def test_divergent_suffix_reuses_prefix_blocks(paged_dir, tiny_model):
    """Shared system prefix + different user suffixes: later requests
    mount the prefix blocks (no prefill) and teacher-force only their
    own suffix; outputs match the cold oracle byte for byte."""
    m, params = tiny_model
    rs = np.random.RandomState(12)
    sysp = rs.randint(0, 1000, (BLOCK,)).astype(np.int32)
    suffixes = [rs.randint(0, 1000, (k,)).astype(np.int32)
                for k in (1, 2, 3)]
    prompts = [np.concatenate([sysp, s]) for s in suffixes]
    eng = GenerationEngine(load_stepwise(paged_dir))
    eng.start()
    try:
        first = eng.submit(prompts[0]).result(timeout=120)
        pre = eng.prefills
        rest = [eng.submit(p).result(timeout=120) for p in prompts[1:]]
    finally:
        eng.close()
    assert eng.prefills == pre, "prefix hits must not prefill"
    for p, g in zip(prompts, [first] + rest):
        assert g == _oracle(m, params, p)


def test_partial_hit_prompt_gets_cached_for_exact_repeat(paged_dir,
                                                         tiny_model):
    """A prompt admitted via a PARTIAL prefix hit is inserted into the
    cache once its teacher-forced suffix lands, so an identical repeat
    exact-hits (re-feeds only the last token) instead of re-forcing
    the suffix forever."""
    m, params = tiny_model
    rs = np.random.RandomState(22)
    sysp = rs.randint(0, 1000, (BLOCK,)).astype(np.int32)
    u1 = rs.randint(0, 1000, (2,)).astype(np.int32)
    u2 = rs.randint(0, 1000, (3,)).astype(np.int32)
    p2 = np.concatenate([sysp, u2])
    eng = GenerationEngine(load_stepwise(paged_dir))
    eng.submit(np.concatenate([sysp, u1]))      # cold: caches sysp chain
    _drain(eng)
    f2 = eng.submit(p2)                         # partial hit on sysp
    _drain(eng)
    saved_before = eng.prefill_tokens_saved
    f3 = eng.submit(p2)                         # must now EXACT-hit
    _drain(eng)
    assert eng.prefill_tokens_saved - saved_before == p2.size - 1, (
        "identical repeat of a partial-hit prompt should exact-hit "
        "(re-feed only the last token)")
    want = _oracle(m, params, p2)
    assert f2.result(timeout=5) == want
    assert f3.result(timeout=5) == want
    eng.close()


def test_cow_on_divergence_protects_cached_blocks(paged_dir,
                                                  tiny_model):
    """An exact-hit request writes its first generated token INTO the
    shared tail block's successor slot — the engine must copy first
    (cow_copies advances) and the cached bytes must stay pure: a third
    identical request still matches the oracle."""
    m, params = tiny_model
    prompt = _prompts(1, seed=13, lo=5, hi=7)[0]     # partial tail block
    assert prompt.size % BLOCK != 0
    eng = GenerationEngine(load_stepwise(paged_dir))
    f1 = eng.submit(prompt)
    _drain(eng)
    cow0 = eng.cow_copies
    f2 = eng.submit(prompt)
    _drain(eng)
    assert eng.cow_copies > cow0, (
        "exact-hit divergence must copy-on-write the shared tail block")
    f3 = eng.submit(prompt)
    _drain(eng)
    want = _oracle(m, params, prompt)
    # all three resolved identically (cached bytes unpolluted)
    for f in (f1, f2, f3):
        assert f.result(timeout=5) == want
    eng.close()


def test_block_exhaustion_fails_one_request_loudly(tmp_path,
                                                   tiny_model):
    """Mid-decode block exhaustion: the request that cannot get a
    block fails with a clear error; its neighbor keeps its blocks and
    finishes byte-identical to the oracle."""
    m, params = tiny_model
    d = str(tmp_path / "tight")
    export_generator(m, params, d, prompt_len=PROMPT_LEN,
                     max_new_tokens=8, batch_size=1, ragged=True,
                     stepwise=True, slots=2, paged=True,
                     block_size=BLOCK, num_blocks=6,   # 5 usable
                     platforms=("cpu",))
    eng = GenerationEngine(load_stepwise(d), prefix_cache=False)
    pa, pb = _prompts(2, seed=14, lo=4, hi=4)
    fa = eng.submit(pa, max_new=8)      # needs 3 blocks over its life
    fb = eng.submit(pb, max_new=8)      # the 6th block does not exist
    _drain(eng)
    assert fa.result(timeout=5) == _oracle(m, params, pa, max_new=8)
    with pytest.raises(BlocksExhaustedError, match="mid-decode"):
        fb.result(timeout=5)
    # the engine still serves: a fresh short request completes
    fc = eng.submit(pa, max_new=1)
    _drain(eng)
    assert fc.result(timeout=5) == _oracle(m, params, pa, max_new=1)
    eng.close()


def test_block_pressure_defers_admission_until_retirement(tmp_path,
                                                          tiny_model):
    """Admission is driven by BLOCK availability, not slot count: a
    request that cannot get its block run waits at the queue head and
    admits after a retirement frees blocks — no deadlock, no loss."""
    m, params = tiny_model
    d = str(tmp_path / "tiny_pool")
    export_generator(m, params, d, prompt_len=PROMPT_LEN,
                     max_new_tokens=2, batch_size=1, ragged=True,
                     stepwise=True, slots=2, paged=True,
                     block_size=BLOCK, num_blocks=4,    # 3 usable
                     platforms=("cpu",))
    eng = GenerationEngine(load_stepwise(d), prefix_cache=False)
    big = _prompts(1, seed=15, lo=PROMPT_LEN, hi=PROMPT_LEN)[0]
    ok = _prompts(1, seed=16, lo=2, hi=2)[0]
    # occupy 2 of 3 blocks so the 2-block prompt cannot fit...
    f_big = eng.submit(big, max_new=1)
    _drain(eng)
    assert f_big.result(timeout=5)      # fits alone (2 blocks + 1 spare)
    # now the unservable case: pool smaller than one prompt's run is
    # impossible by export validation, so exercise the deferral path:
    # a long-lived request holds blocks; a queued one waits, then runs
    f1 = eng.submit(big, max_new=2)
    f2 = eng.submit(big, max_new=2)
    _drain(eng)
    assert f1.result(timeout=5) == f2.result(timeout=5) \
        == _oracle(m, params, big, max_new=2)
    eng.close()


def test_paged_capacity_2x_slab_at_equal_pool_bytes(tmp_path,
                                                    tiny_model):
    """THE capacity claim: at equal pool bytes, paged admission holds
    >= 2x the slab slot count of short concurrent requests (slab
    reserves slots x T; paged reserves actual residency)."""
    m, params = tiny_model
    slab_slots = 2
    total = PROMPT_LEN + MAX_NEW                     # 13
    blocks_per_slot = -(-total // BLOCK)             # 4
    usable = slab_slots * blocks_per_slot            # slab bytes, blocks
    d = str(tmp_path / "cap")
    export_generator(m, params, d, prompt_len=PROMPT_LEN,
                     max_new_tokens=MAX_NEW, batch_size=1, ragged=True,
                     stepwise=True, slots=4 * slab_slots, paged=True,
                     block_size=BLOCK, num_blocks=1 + usable,
                     platforms=("cpu",))
    eng = GenerationEngine(load_stepwise(d), prefix_cache=False)
    # short prompts: 1 block each
    for p in _prompts(4 * slab_slots, seed=17, lo=2, hi=3):
        eng.submit(p, max_new=MAX_NEW)
    eng._admit()
    admitted = len(eng._live)
    assert admitted >= 2 * slab_slots, (
        f"paged pool admitted {admitted} concurrent requests; the slab "
        f"pool of equal bytes holds {slab_slots}")
    assert admitted == usable                        # 1 block per prompt
    eng.close()


def test_paged_stats_block_observability(paged_dir):
    eng = GenerationEngine(load_stepwise(paged_dir))
    eng.submit(_prompts(1, seed=18)[0])
    _drain(eng)
    s = eng.stats()
    for key in ("blocks_total", "blocks_free", "bytes_resident",
                "prefix_cache_hits", "prefix_cache_misses",
                "prefill_tokens_saved", "cow_copies", "block_size"):
        assert key in s, key
    assert s["paged"] is True
    assert s["blocks_total"] == 47
    assert 0 <= s["blocks_free"] <= s["blocks_total"]
    resident = s["blocks_total"] - s["blocks_free"]
    assert s["bytes_resident"] == resident * eng._block_bytes
    eng.close()


def test_shared_block_freed_only_at_last_release(paged_dir, tiny_model):
    """Engine-level refcount contract: a block shared by the prefix
    cache and TWO mounted slots survives cache eviction and the first
    retirement; it frees only when the last owner lets go."""
    m, params = tiny_model
    rs = np.random.RandomState(19)
    sysp = rs.randint(0, 1000, (BLOCK,)).astype(np.int32)   # 1 full block
    eng = GenerationEngine(load_stepwise(paged_dir))
    eng.submit(sysp, max_new=1)
    _drain(eng)                                  # cold: caches the block
    free_with_cache = eng.blocks.free_count
    blk = None
    for (blocks, n) in eng.prefix_cache._entries.values():
        if n == BLOCK:
            blk = blocks[0]
    assert blk is not None
    assert eng.blocks.refcount(blk) == 1                 # cache only
    # two hit admissions mount it (no steps run yet)
    a = np.concatenate([sysp, rs.randint(0, 1000, (1,)).astype(np.int32)])
    b = np.concatenate([sysp, rs.randint(0, 1000, (2,)).astype(np.int32)])
    fa, fb = eng.submit(a), eng.submit(b)
    eng._admit()
    assert eng.blocks.refcount(blk) == 3
    eng.prefix_cache.evict(10 ** 9)                      # drop ALL entries
    assert eng.blocks.refcount(blk) == 2                 # slots still hold
    assert eng.blocks.free_count < eng.blocks.usable
    _drain(eng)                                          # both retire
    # the retired slots re-inserted their (partial-hit) prompts, so
    # the cache again holds blk — drop it to see the LAST release free
    eng.prefix_cache.evict(10 ** 9)
    assert eng.blocks.refcount(blk) == 0                 # last release
    assert eng.blocks.free_count == eng.blocks.usable
    assert fa.result(timeout=5) == _oracle(m, params, a)
    assert fb.result(timeout=5) == _oracle(m, params, b)
    eng.close()


def test_http_paged_end_to_end_parity_and_stats(paged_dir):
    """The REST layer over a paged artifact: auto scheduler on,
    concurrent posts byte-identical to --scheduler off, /stats carries
    the block keys, and --prefix_cache off serves cold."""
    n = 6
    prompts = _prompts(n, seed=20)
    results: list = [None] * n

    def post(port, name, payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/models/{name}:generate",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read())

    with PredictServer(paged_dir) as srv:
        assert srv.scheduler == "on" and srv.engine.paged

        def worker(i):
            results[i] = post(
                srv.port, srv.name,
                {"inputs": {"input_ids": [prompts[i].tolist()]}}
            )["generations"][0]

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/stats") as r:
            stats = json.loads(r.read())["generate"]
    assert stats["paged"] is True
    assert stats["blocks_total"] > 0
    assert stats["requests_done"] == n

    with PredictServer(paged_dir, scheduler="off") as srv:
        for i, p in enumerate(prompts):
            ids = np.zeros((PROMPT_LEN,), np.int32)
            mask = np.zeros((PROMPT_LEN,), np.int32)
            ids[:p.size] = p
            mask[:p.size] = 1
            want = post(srv.port, srv.name,
                        {"inputs": {"input_ids": [ids.tolist()],
                                    "prompt_mask": [mask.tolist()]}}
                        )["generations"][0]
            assert results[i] == want, f"request {i} diverged"

    with PredictServer(paged_dir, prefix_cache=False) as srv:
        assert srv.engine.prefix_cache is None
        got = post(srv.port, srv.name,
                   {"inputs": {"input_ids": [prompts[0].tolist()]}}
                   )["generations"][0]
        assert got == results[0]


def test_engine_retry_after_uses_measured_steps(paged_dir):
    """After real steps the 429 Retry-After reflects the measured EMA,
    not the old queue-depth guess."""
    eng = GenerationEngine(load_stepwise(paged_dir))
    assert eng._retry_after() == 1.0          # no signal yet
    eng.submit(_prompts(1, seed=21, lo=4, hi=6)[0], max_new=MAX_NEW)
    _drain(eng)
    assert eng._retry.ema_step_s is not None
    assert eng._retry_after() >= 0.1
    eng.close()
