"""Serving export (serving.py): jax.export artifacts with baked params.

Contract: the artifact is self-contained (deserialized and run without
the model object), numerically identical to the live forward, and
batch-polymorphic (one artifact, any leading batch size).
"""

import json
import os

import jax
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import TrainConfig
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.serving import (ServableModel,
                                                        export_model,
                                                        load_servable,
                                                        serving_signature)


def _init(model):
    out = model.init(jax.random.key(0))
    return out if isinstance(out, tuple) else (out, {})


@pytest.mark.parametrize("name", ["mlp", "lenet", "bert_tiny",
                                  "moe_bert_tiny",
                                  "pipe_bert_tiny",
                                  "pipe_moe_bert_tiny"])
def test_export_roundtrip_matches_live_forward(name, tmp_path):
    cfg = TrainConfig(model=name)
    m = get_model(name, cfg)
    params, extras = _init(m)
    d = str(tmp_path / name)
    artifact = export_model(m, params, extras, d, platforms=("cpu",),
                            batch_size=4)
    assert os.path.exists(artifact)

    sv = load_servable(d)
    feats = serving_signature(m.dummy_batch(4))
    got = np.asarray(sv(feats))
    want = np.asarray(m.apply(params, extras, feats, train=False)[0])
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_batch_polymorphism(tmp_path):
    cfg = TrainConfig(model="mlp")
    m = get_model("mlp", cfg)
    params, extras = _init(m)
    d = str(tmp_path / "m")
    export_model(m, params, extras, d, platforms=("cpu",), batch_size=8)
    sv = load_servable(d)
    for bs in (1, 3, 32):
        feats = serving_signature(m.dummy_batch(bs))
        assert sv(feats).shape == (bs, 10)


def test_metadata_written(tmp_path):
    cfg = TrainConfig(model="mlp")
    m = get_model("mlp", cfg)
    params, extras = _init(m)
    d = str(tmp_path / "m")
    export_model(m, params, extras, d, platforms=("cpu",))
    meta = json.load(open(os.path.join(d, "export.json")))
    assert meta["model"] == "mlp"
    assert meta["batch_polymorphic"] is True
    assert "x" in meta["input_signature"]
    assert meta["param_count"] == sum(
        int(np.size(p)) for p in jax.tree_util.tree_leaves(params))
    sv = ServableModel(d)
    assert sv.input_signature == meta["input_signature"]


def test_artifact_is_self_contained(tmp_path):
    """The servable must run from the serialized bytes alone — no model
    object, params, or registry involved."""
    cfg = TrainConfig(model="mlp")
    m = get_model("mlp", cfg)
    params, extras = _init(m)
    d = str(tmp_path / "m")
    export_model(m, params, extras, d, platforms=("cpu",))
    feats = serving_signature(m.dummy_batch(2))
    want = np.asarray(m.apply(params, extras, feats, train=False)[0])
    del m, params, extras

    from jax import export as jax_export
    with open(os.path.join(d, "model.stablehlo"), "rb") as f:
        rehydrated = jax_export.deserialize(f.read())
    got = np.asarray(rehydrated.call(feats))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_cli_export(tmp_path):
    from distributed_tensorflow_example_tpu.cli.train import main
    exp = str(tmp_path / "exp")
    rc = main(["--model", "mlp", "--train_steps", "3",
               "--batch_size", "32", "--export_dir", exp])
    assert rc == 0
    sv = load_servable(exp)
    cfg = TrainConfig(model="mlp")
    m = get_model("mlp", cfg)
    feats = serving_signature(m.dummy_batch(4))
    assert sv(feats).shape == (4, 10)


def test_cli_eval_only_export(tmp_path):
    """Export-from-checkpoint: restore an existing run and ship the
    servable without retraining."""
    from distributed_tensorflow_example_tpu.cli.train import main
    ck = str(tmp_path / "ck")
    rc = main(["--model", "mlp", "--train_steps", "4", "--batch_size",
               "32", "--ckpt_dir", ck, "--save_steps", "4"])
    assert rc == 0
    exp = str(tmp_path / "exp")
    rc = main(["--model", "mlp", "--eval_only", "--ckpt_dir", ck,
               "--export_dir", exp, "--batch_size", "32"])
    assert rc == 0
    sv = load_servable(exp)
    cfg = TrainConfig(model="mlp")
    feats = serving_signature(get_model("mlp", cfg).dummy_batch(2))
    assert sv(feats).shape == (2, 10)


def test_cli_export_dir_fail_fast(tmp_path):
    """An uncreatable --export_dir dies before training, not after.
    (A plain file at the path makes makedirs fail even for root, which
    ignores permission bits.)"""
    from distributed_tensorflow_example_tpu.cli.train import main
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("x")
    with pytest.raises(SystemExit, match="export_dir"):
        main(["--model", "mlp", "--train_steps", "1",
              "--export_dir", str(blocker)])


def test_exported_bert_takes_feature_keys_only(tmp_path):
    cfg = TrainConfig(model="bert_tiny")
    m = get_model("bert_tiny", cfg)
    params, extras = _init(m)
    d = str(tmp_path / "b")
    export_model(m, params, extras, d, platforms=("cpu",))
    meta = json.load(open(os.path.join(d, "export.json")))
    assert "masked_labels" not in meta["input_signature"]
    assert "masked_weights" not in meta["input_signature"]
    assert "input_ids" in meta["input_signature"]


def test_export_bf16_params(tmp_path):
    """bf16 param_dtype exports and serves (StableHLO serializes the
    bf16 constants; logits still come out f32)."""
    cfg = TrainConfig(model="mlp", param_dtype="bfloat16",
                      dtype="bfloat16")
    m = get_model("mlp", cfg)
    params, extras = _init(m)
    d = str(tmp_path / "bf16")
    export_model(m, params, extras, d, platforms=("cpu",))
    sv = load_servable(d)
    feats = serving_signature(m.dummy_batch(4))
    out = np.asarray(sv(feats))
    assert out.dtype == np.float32
    want = np.asarray(m.apply(params, extras, feats, train=False)[0])
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)


def test_moe_export_falls_back_to_static_batch(tmp_path):
    """MoE capacity is a static function of the token count: the
    symbolic-batch trace fails, the export falls back to a static
    artifact (recorded in metadata) that serves exactly batch_size."""
    m = get_model("moe_bert_tiny", TrainConfig(model="moe_bert_tiny"))
    params, extras = _init(m)
    d = str(tmp_path / "moe")
    export_model(m, params, extras, d, platforms=("cpu",), batch_size=4)
    meta = json.load(open(os.path.join(d, "export.json")))
    assert meta["batch_polymorphic"] is False
    sv = load_servable(d)
    feats = serving_signature(m.dummy_batch(4))
    np.testing.assert_allclose(
        np.asarray(sv(feats)),
        np.asarray(m.apply(params, extras, feats, train=False)[0]),
        rtol=1e-5, atol=1e-5)


def test_gpt_exports_and_serves(tmp_path):
    """The causal-LM family rides the generic export path: logits from
    the StableHLO artifact match the live model."""
    from distributed_tensorflow_example_tpu.models import get_model
    m = get_model("gpt_tiny", TrainConfig(model="gpt_tiny"))
    params = m.init(jax.random.key(0))
    d = str(tmp_path / "gpt")
    export_model(m, params, {}, d, platforms=("cpu",))
    sv = load_servable(d)
    feats = serving_signature(m.dummy_batch(2))
    out = np.asarray(sv(feats))
    want = np.asarray(m.apply(params, {}, feats, train=False)[0])
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)


def test_generator_artifact_round_trip(tmp_path):
    """export_generator serializes the WHOLE generation (prefill + the
    KV-cache scan) as one StableHLO program: greedy tokens equal the
    live model's, and a sampled artifact is deterministic per rng and
    equal to the live sampled generate."""
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.serving import export_generator
    import jax.numpy as jnp
    m = get_model("gpt_tiny", TrainConfig(model="gpt_tiny"))
    params = m.init(jax.random.key(0))
    rs = np.random.RandomState(0)
    prompt = jnp.asarray(rs.randint(0, 1000, (2, 8), dtype=np.int32))

    d = str(tmp_path / "greedy")
    export_generator(m, params, d, prompt_len=8, max_new_tokens=6,
                     batch_size=2, platforms=("cpu",))
    sv = load_servable(d)
    assert sv.meta["kind"] == "generator"
    toks = np.asarray(sv({"input_ids": prompt}))
    np.testing.assert_array_equal(toks,
                                  np.asarray(m.generate(params, prompt, 6)))

    d2 = str(tmp_path / "sampled")
    export_generator(m, params, d2, prompt_len=8, max_new_tokens=6,
                     batch_size=2, temperature=0.8, platforms=("cpu",))
    sv2 = load_servable(d2)
    key = jax.random.key_data(jax.random.key(7))
    t1 = np.asarray(sv2({"input_ids": prompt, "rng": key}))
    np.testing.assert_array_equal(
        t1, np.asarray(sv2({"input_ids": prompt, "rng": key})))
    np.testing.assert_array_equal(
        t1, np.asarray(m.generate(params, prompt, 6, temperature=0.8,
                                  rng=jax.random.key(7))))


def test_generator_artifact_with_eos_topk_ragged(tmp_path):
    """The full knob surface survives export: a ragged top-k sampling
    artifact with EOS early-stop reproduces the live generate call
    with identical knobs (rng via raw key data)."""
    from distributed_tensorflow_example_tpu.serving import export_generator
    import jax.numpy as jnp
    m = get_model("gpt_tiny", TrainConfig(model="gpt_tiny"))
    params, _ = _init(m)
    rs = np.random.RandomState(1)
    ids = rs.randint(1, 1000, (2, 8), dtype=np.int32)
    mask = np.asarray([[1] * 8, [1] * 5 + [0] * 3], np.int32)
    ids[1, 5:] = 0
    free = np.asarray(m.generate(params, jnp.asarray(ids), 6,
                                 prompt_mask=jnp.asarray(mask)))
    eos = int(free[0, 2])

    d = str(tmp_path / "gen")
    export_generator(m, params, d, prompt_len=8, max_new_tokens=6,
                     batch_size=2, temperature=0.9, top_k=50,
                     eos_id=eos, pad_id=-7, ragged=True,
                     platforms=("cpu",))
    sv = load_servable(d)
    assert sv.meta["ragged"] and sv.meta["eos_id"] == eos
    key = jax.random.key_data(jax.random.key(11))
    got = np.asarray(sv({"input_ids": jnp.asarray(ids),
                         "prompt_mask": jnp.asarray(mask), "rng": key}))
    want = np.asarray(m.generate(params, jnp.asarray(ids), 6,
                                 temperature=0.9, top_k=50, eos_id=eos,
                                 pad_id=-7, prompt_mask=jnp.asarray(mask),
                                 rng=jax.random.key(11)))
    np.testing.assert_array_equal(got, want)
