"""Speculative decoding (round 16): drafter/estimator units, the
engine's draft-and-verify loop, stop_sequences, and the HTTP surface.

The headline contract is EXACTNESS: greedy output with ``spec_tokens=K``
on is byte-identical to speculation off — tested here at the engine and
HTTP levels across 8 concurrent ragged requests, including under int8
decode weights + int8 paged KV (the load-harness level rides the
``serving_load --smoke`` spec legs). The satellites pin the
``stop_sequences`` truncation boundary, the Retry-After
tokens-per-dispatch math, the spec-off bitwise no-op, and the
auto-off/validation surface of the knobs.
"""

import json
import os
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "experiments"))

from serving_load import build_export  # noqa: E402

from distributed_tensorflow_example_tpu.serving import \
    load_stepwise  # noqa: E402
from distributed_tensorflow_example_tpu.serving_batch import (  # noqa: E402
    GenerationEngine, NgramDrafter, RetryAfterEstimator)
from distributed_tensorflow_example_tpu.serving_http import \
    PredictServer  # noqa: E402

SLOTS = 8
PROMPT_LEN = 12
MAX_NEW = 16


@pytest.fixture(scope="module")
def spec_dir(tmp_path_factory):
    """One verify-program paged export (slots=8 — the 8-concurrent-
    ragged-requests acceptance shape) shared by the engine and HTTP
    tests."""
    d = str(tmp_path_factory.mktemp("spec"))
    vocab = build_export(d, prompt_len=PROMPT_LEN, max_new=MAX_NEW,
                         slots=SLOTS, seed=0, paged=True, block_size=4,
                         spec_tokens=4)
    return d, vocab


@pytest.fixture(scope="module")
def spec_int8_dir(tmp_path_factory):
    """The fully quantized twin: int8 decode weights + int8 paged KV
    pool + the verify program — speculation must stay EXACT against
    the same artifact's spec-off path (the int8-vs-bf16 drift bound is
    a separate, pre-existing contract)."""
    d = str(tmp_path_factory.mktemp("spec_int8"))
    vocab = build_export(d, prompt_len=PROMPT_LEN, max_new=MAX_NEW,
                         slots=SLOTS, seed=0, paged=True, block_size=4,
                         weight_quant="int8", kv_cache_dtype="int8",
                         spec_tokens=4)
    return d, vocab


def ragged_prompts(vocab: int, n: int = SLOTS, seed: int = 7):
    """n mixed-length repetitive prompts (the drafter's workload) —
    'ragged' in the engine sense: every length differs, nothing padded
    by the client."""
    rs = np.random.RandomState(seed)
    pattern = rs.randint(0, vocab, (3,)).astype(np.int32)
    return [np.tile(pattern, 5)[:int(rs.randint(2, PROMPT_LEN + 1))]
            .astype(np.int32) for _ in range(n)]


@pytest.fixture(scope="module")
def oracle(spec_dir):
    """ONE spec-off engine pass over the standard 8 ragged prompts —
    the byte-parity oracle several tests compare against (greedy rows
    are computationally independent, so any test may also compare a
    prompt SUBSET against the matching oracle rows)."""
    d, vocab = spec_dir
    prompts = ragged_prompts(vocab)
    outs, stats, _ = run_engine(d, prompts, spec=0)
    return prompts, outs, stats


def run_engine(d, prompts, *, spec: int, max_new: int = MAX_NEW, **kw):
    eng = GenerationEngine(load_stepwise(d), prefix_cache=False,
                           spec_tokens=spec).start()
    try:
        handles = [eng.submit(p, max_new=max_new, **kw)
                   for p in prompts]
        outs = [h.result(timeout=300) for h in handles]
        stats = eng.stats()
        assert eng.blocks.in_use == 0, "blocks leaked past retirement"
        return outs, stats, [h.timings for h in handles]
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# units: the drafter and the Retry-After math
# ---------------------------------------------------------------------------

def test_ngram_drafter_proposes_continuation_of_latest_match():
    dr = NgramDrafter([1, 2, 3, 9, 1, 2, 3, 7, 1, 2, 3])
    # suffix [1,2,3] last PRIOR occurrence starts at index 4 -> the
    # continuation is [7, 1, 2] (most recent match wins, not the first)
    assert dr.propose(3) == [7, 1, 2]
    assert dr.propose(1) == [7]


def test_ngram_drafter_never_matches_its_own_suffix():
    # the only occurrence of every suffix IS the suffix — no proposal
    assert NgramDrafter([1, 2, 3, 4]).propose(4) == []
    # a 1-token context has nothing prior to continue from
    assert NgramDrafter([5]).propose(2) == []


def test_ngram_drafter_extends_incrementally():
    dr = NgramDrafter([4, 5, 6])
    assert dr.propose(2) == []
    for t in (4, 5):
        dr.extend(t)
    # context [4,5,6,4,5]: suffix [4,5] recurs at 0 -> continuation
    # [6, 4] (the proposal may include the current last token — it is
    # still a prediction about what FOLLOWS the suffix)
    assert dr.propose(2) == [6, 4]
    assert dr.propose(1) == [6]
    assert len(dr) == 5


def test_ngram_drafter_falls_back_to_shorter_ngrams():
    # no 3- or 2-gram recurs, but the 1-gram [2] does (latest at
    # index 2 -> continuation 9)
    dr = NgramDrafter([2, 8, 2, 9, 3, 2], max_ngram=3)
    assert dr.propose(2) == [9, 3]


def test_ngram_drafter_validates_max_ngram():
    with pytest.raises(ValueError, match="max_ngram"):
        NgramDrafter([1], max_ngram=0)


def test_retry_after_counts_accepted_tokens_per_dispatch():
    """The satellite fix: steps-to-free must count accepted TOKENS per
    dispatch, not dispatches — at accept-driven 3 tokens/dispatch, 30
    remaining row-steps are ~10 dispatches, not 30 (the pre-fix
    estimate overestimated Retry-After by ~1/accept_rate)."""
    est = RetryAfterEstimator(alpha=0.5)
    assert est.dispatches_for(30.0) == 30.0        # spec-off identity
    for _ in range(64):
        est.observe_advance(3.0)
    assert est.ema_tokens_per_dispatch == pytest.approx(3.0, rel=1e-3)
    assert est.dispatches_for(30.0) == pytest.approx(10.0, rel=1e-2)
    # the estimate itself consumes the converted hint
    est.observe(0.1)
    assert est.estimate(est.dispatches_for(30.0)) \
        == pytest.approx(1.0, rel=0.05)


def test_retry_after_advance_clamped_at_one_dispatch_per_step():
    est = RetryAfterEstimator(alpha=1.0)
    est.observe_advance(0.25)      # a degenerate feed must not blow up
    assert est.dispatches_for(8.0) == 8.0


# ---------------------------------------------------------------------------
# engine level: exactness across 8 concurrent ragged requests
# ---------------------------------------------------------------------------

def test_engine_spec_greedy_byte_parity_and_dispatch_win(spec_dir,
                                                         oracle):
    d, _ = spec_dir
    prompts, off, s_off = oracle
    on, s_on, timings = run_engine(d, prompts, spec=4)
    assert on == off, "speculative greedy output diverged"
    assert s_off["verify_steps"] == 0
    assert s_on["spec_accepted"] > 0 and s_on["accept_rate"] > 0
    # the decode economy: strictly fewer total shared dispatches, and
    # strictly fewer verify dispatches than emitted tokens
    assert (s_on["decode_steps"] + s_on["verify_steps"]
            < s_off["decode_steps"])
    assert s_on["verify_steps"] < s_on["tokens_out"]
    # rejections genuinely happened — so the pos-rewind/trailing-block
    # path ran, and the exact in_use == 0 check inside run_engine plus
    # the BlockPool's own double-release assertions covered it
    assert s_on["spec_proposed"] > s_on["spec_accepted"]
    # per-request accounting reaches the timings breakdown
    assert sum(t["spec_accepted"] for t in timings) \
        == s_on["spec_accepted"]


def test_engine_spec_exact_under_int8_weights_and_kv(spec_int8_dir):
    """The acceptance criterion's quant leg: speculation must stay
    byte-exact when the verify program runs int8 stacked weights AND
    the int8 paged pool (quantize-on-write + fused-dequant gathers) —
    the verify body is the decode body over expanded rows, so the
    whole quant surface rides along."""
    d, vocab = spec_int8_dir
    prompts = ragged_prompts(vocab)
    off, s_off, _ = run_engine(d, prompts, spec=0)
    on, s_on, _ = run_engine(d, prompts, spec=4)
    assert on == off, "int8 speculative output diverged from int8 oracle"
    assert s_on["spec_accepted"] > 0
    assert (s_on["decode_steps"] + s_on["verify_steps"]
            < s_off["decode_steps"])


def test_engine_spec_exact_for_sampled_requests(spec_dir):
    """Sampled requests never draft (the exact rule is greedy-only):
    their per-seed determinism contract is untouched and no verify
    dispatch carries their lanes beyond width 1."""
    d, vocab = spec_dir
    prompts = ragged_prompts(vocab, n=4)
    kw = dict(temperature=0.8, top_k=5, seed=11)
    off, _, _ = run_engine(d, prompts, spec=0, **kw)
    on, s_on, _ = run_engine(d, prompts, spec=4, **kw)
    assert on == off
    assert s_on["spec_proposed"] == 0 and s_on["verify_steps"] == 0


def test_engine_spec_off_is_bitwise_noop(spec_dir, tmp_path):
    """--spec_tokens 0 (the default) over a verify-program artifact is
    a BITWISE no-op: identical outputs, identical dispatch counts, and
    identical pool bytes vs the same engine over a plain paged export
    of the same seed (zero verify dispatches, zero drafting work)."""
    d, vocab = spec_dir
    plain = str(tmp_path / "plain")
    build_export(plain, prompt_len=PROMPT_LEN, max_new=MAX_NEW,
                 slots=SLOTS, seed=0, paged=True, block_size=4)
    prompts = ragged_prompts(vocab, n=SLOTS)

    def run_preloaded(dir_):
        """Pre-load the queue before start() so the admission wave —
        and therefore the dispatch sequence — is deterministic."""
        eng = GenerationEngine(load_stepwise(dir_), prefix_cache=False)
        handles = [eng.submit(p, max_new=8) for p in prompts]
        eng.start()
        try:
            outs = [h.result(timeout=300) for h in handles]
            s = eng.stats()
            pool = {k: np.asarray(v) for k, v in eng._pool.items()}
            return outs, (s["decode_steps"], s["prefills"],
                          s["verify_steps"]), pool
        finally:
            eng.close()

    outs_a, counts_a, pool_a = run_preloaded(d)
    outs_b, counts_b, pool_b = run_preloaded(plain)
    assert outs_a == outs_b
    assert counts_a == counts_b and counts_a[2] == 0
    assert sorted(pool_a) == sorted(pool_b)
    for k in pool_a:
        assert np.array_equal(pool_a[k], pool_b[k]), \
            f"pool tensor {k} diverged bitwise under spec-off"


def test_engine_spec_knob_validation(spec_dir, tmp_path):
    d, _ = spec_dir
    sw = load_stepwise(d)
    with pytest.raises(ValueError, match="spec_tokens"):
        GenerationEngine(sw, spec_tokens=1)
    with pytest.raises(ValueError, match="verify width"):
        GenerationEngine(sw, spec_tokens=9)
    plain = str(tmp_path / "noverify")
    build_export(plain, prompt_len=8, max_new=4, slots=2, seed=0,
                 paged=True, block_size=4)
    with pytest.raises(ValueError, match="verify program"):
        GenerationEngine(load_stepwise(plain), spec_tokens=4)


def test_engine_per_request_spec_optout_and_cap(spec_dir, oracle):
    d, _ = spec_dir
    prompts, off, _ = oracle
    prompts = prompts[:4]
    # spec_tokens=0 per request: no drafting at all, bytes identical
    # to the oracle's matching rows (rows are independent)
    outs, s, _ = run_engine(d, prompts, spec=4, spec_tokens=0)
    assert s["spec_proposed"] == 0 and s["verify_steps"] == 0
    assert outs == off[:4]
    # a cap above the engine width is a loud client error
    eng = GenerationEngine(load_stepwise(d), spec_tokens=4)
    try:
        with pytest.raises(ValueError, match="exceeds"):
            eng.submit(prompts[0], spec_tokens=9)
        with pytest.raises(ValueError, match="spec_tokens"):
            eng.submit(prompts[0], spec_tokens=1)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# stop_sequences: truncation at the boundary, spec on and off
# ---------------------------------------------------------------------------

def _expected_stopped(base, ss, pad):
    """Host-side recomputation of the truncation contract: base
    outputs cut before the FIRST completed stop-sequence match (first
    in list order per position), then padded to max_new."""
    out = []
    for b in base:
        exp = list(b)
        done = False
        for i in range(1, len(exp) + 1):
            for s in ss:
                if i >= len(s) and exp[i - len(s):i] == list(s):
                    exp = exp[:i - len(s)] + [pad] * (
                        MAX_NEW - (i - len(s)))
                    done = True
                    break
            if done:
                break
        out.append(exp)
    return out


def test_stop_sequences_truncate_at_boundary(spec_dir, oracle):
    d, _ = spec_dir
    prompts, base, _ = oracle
    # stop on the 2-token suffix that opens request 0's output: its
    # result must be truncated to NOTHING (match excluded), padded to
    # max_new with pad_id
    ss = [list(map(int, base[0][:2]))]
    outs, _, _ = run_engine(d, prompts, spec=0, stop_sequences=ss)
    pad = load_stepwise(d).meta.get("pad_id", 0)
    assert outs[0] == [pad] * MAX_NEW
    assert outs == _expected_stopped(base, ss, pad)


def test_stop_sequences_identical_with_speculation(spec_dir, oracle):
    d, _ = spec_dir
    prompts, base, _ = oracle
    # stop sequences drawn from the middle of a real output, so a
    # match routinely completes INSIDE an accepted draft run; the
    # speculative truncation must land exactly where the recomputed
    # non-speculative contract says (== where the spec-off engine
    # lands, per test_stop_sequences_truncate_at_boundary)
    donor = max(base, key=len)
    ss = [list(map(int, donor[2:4])), list(map(int, base[0][:1]))]
    pad = load_stepwise(d).meta.get("pad_id", 0)
    on, _, _ = run_engine(d, prompts, spec=4, stop_sequences=ss)
    assert on == _expected_stopped(base, ss, pad), \
        "stop_sequences boundary moved under speculation"


def test_stop_sequences_validation(spec_dir):
    d, vocab = spec_dir
    eng = GenerationEngine(load_stepwise(d))
    try:
        p = np.array([1, 2, 3], np.int32)
        with pytest.raises(ValueError, match="stop_sequences"):
            eng.submit(p, stop_sequences="abc")
        with pytest.raises(ValueError, match="non-empty"):
            eng.submit(p, stop_sequences=[[]])
        with pytest.raises(ValueError, match="non-integer"):
            eng.submit(p, stop_sequences=[[1, "x"]])
        with pytest.raises(ValueError, match="at most 16"):
            eng.submit(p, stop_sequences=[[1]] * 17)
        with pytest.raises(ValueError, match="64"):
            eng.submit(p, stop_sequences=[[1] * 65])
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# HTTP level
# ---------------------------------------------------------------------------

def _post(port, name, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/{name}:generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=300) as r:
        return json.loads(r.read())


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return r.read().decode()


def _serve_concurrent(d, prompts, *, spec_tokens, **payload_kw):
    outs: list = [None] * len(prompts)
    with PredictServer(d, prefix_cache=False,
                       spec_tokens=spec_tokens) as srv:
        def client(i):
            outs[i] = _post(srv.port, srv.name, {
                "inputs": {"input_ids": [prompts[i].tolist()]},
                "max_new": 10, **payload_kw})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = json.loads(_get(srv.port, "/stats"))
        prom = _get(srv.port, "/metrics")
    return outs, stats, prom


def test_http_spec_parity_stats_and_metrics(spec_dir):
    """8 concurrent ragged :generate requests: byte parity spec-on vs
    spec-off, accept_rate visible in /stats AND /metrics, and
    spec_accepted riding every response's timings row."""
    d, vocab = spec_dir
    prompts = ragged_prompts(vocab)
    off, _, _ = _serve_concurrent(d, prompts, spec_tokens=0)
    on, stats, prom = _serve_concurrent(d, prompts, spec_tokens=4)
    assert [o["generations"] for o in on] \
        == [o["generations"] for o in off]
    g = stats["generate"]
    assert g["spec_tokens"] == 4
    assert g["spec_accepted"] > 0 and g["accept_rate"] > 0
    assert g["verify_steps"] < g["tokens_out"]
    assert "serving_spec_accept_rate" in prom
    assert "serving_verify_steps_total" in prom
    assert all("spec_accepted" in o["timings"][0] for o in on)
    assert sum(o["timings"][0]["spec_accepted"] for o in on) \
        == g["spec_accepted"]


def test_http_payload_spec_and_stop_knobs(spec_dir):
    d, vocab = spec_dir
    prompts = ragged_prompts(vocab, n=2)
    with PredictServer(d, prefix_cache=False, spec_tokens=4) as srv:
        base = _post(srv.port, srv.name, {
            "inputs": {"input_ids": [prompts[0].tolist()]},
            "max_new": 8})["generations"][0]
        # per-request opt-out serves identically (exactness, again)
        opt = _post(srv.port, srv.name, {
            "inputs": {"input_ids": [prompts[0].tolist()]},
            "max_new": 8, "spec_tokens": 0})["generations"][0]
        assert opt == base
        # stop_sequences truncates at the boundary over HTTP
        stop = _post(srv.port, srv.name, {
            "inputs": {"input_ids": [prompts[0].tolist()]},
            "max_new": 8, "stop_sequences": [base[:2]]})
        pad = srv.servable.meta.get("pad_id", 0)
        assert stop["generations"][0] == [pad] * 8
        # invalid knobs are clean 400s naming the field
        for bad in ({"spec_tokens": 99}, {"spec_tokens": 1},
                    {"stop_sequences": [[]]},
                    {"stop_sequences": "x"}):
            try:
                _post(srv.port, srv.name, {
                    "inputs": {"input_ids": [prompts[0].tolist()]},
                    "max_new": 4, **bad})
                raise AssertionError(f"{bad} was not rejected")
            except urllib.error.HTTPError as e:
                assert e.code == 400, (bad, e.code)


def test_http_engine_only_knobs_rejected_on_scheduler_off(spec_dir):
    """The monolithic (scheduler-off) path cannot honor
    stop_sequences or spec_tokens — a payload carrying them must be a
    clear 400 naming the scheduler requirement, never a 200 that
    silently dropped the contract."""
    d, _ = spec_dir
    with PredictServer(d, scheduler="off") as srv:
        for bad in ({"stop_sequences": [[1, 2]]}, {"spec_tokens": 2}):
            try:
                _post(srv.port, srv.name, {
                    "inputs": {"input_ids": [[1, 2, 3]]}, **bad})
                raise AssertionError(f"{bad} accepted on scheduler-off")
            except urllib.error.HTTPError as e:
                assert e.code == 400
                assert "scheduler" in json.loads(e.read())["error"]


def test_http_spec_tokens_auto_off_without_verify_program(tmp_path):
    """--spec_tokens over an artifact without a verify program serves
    spec-off (warning, not refusal) — the auto-off contract."""
    d = str(tmp_path / "plain")
    vocab = build_export(d, prompt_len=8, max_new=4, slots=2, seed=0,
                         paged=True, block_size=4)
    with PredictServer(d, spec_tokens=4) as srv:
        assert srv.engine is not None
        assert srv.engine.spec_tokens == 0
        out = _post(srv.port, srv.name, {
            "inputs": {"input_ids": [[1, 2, 3]]}, "max_new": 2})
        assert len(out["generations"][0]) == 2
        g = json.loads(_get(srv.port, "/stats"))["generate"]
        assert g["spec_tokens"] == 0 and g["verify_steps"] == 0


def test_http_spec_tokens_clamped_to_artifact_width(spec_dir):
    d, _ = spec_dir
    with PredictServer(d, spec_tokens=9) as srv:
        assert srv.engine.spec_tokens == 4
