"""obs/stitch.py + obs/flightrec.py unit tests: NTP-style clock-offset
estimation and the fleet stitcher with INJECTED clocks (no wall-clock
sleeps), and the flight recorder's bundle format, atomicity, rate
limiting (injected clock), and counters.
"""

import json
import os
import threading

import pytest

from distributed_tensorflow_example_tpu.obs import stitch
from distributed_tensorflow_example_tpu.obs.flightrec import (
    FlightRecorder, config_fingerprint)
from distributed_tensorflow_example_tpu.obs.registry import Registry
from distributed_tensorflow_example_tpu.obs.trace import (
    TraceRecorder, recorder, set_recorder)


@pytest.fixture
def fresh_recorder():
    old = recorder()
    rec = set_recorder(TraceRecorder())
    yield rec
    set_recorder(old)


# ------------------------------------------------------ offset estimate
def test_estimate_offset_median_from_injected_clocks():
    """offset = remote_now - probe midpoint; the MEDIAN over samples
    rejects the occasional slow (asymmetric-delay) probe."""
    # remote clock runs 100 s ahead; probes take 2 ms each
    samples = [(t, t + 0.002, (t + 0.001) + 100.0)
               for t in (5.0, 6.0, 7.0, 8.0)]
    assert stitch.estimate_offset(samples) == pytest.approx(100.0)
    # one pathological probe (5 s stall AFTER the remote stamped its
    # clock — worst-case asymmetry) must not drag the estimate
    samples.append((9.0, 14.0, 9.001 + 100.0))
    assert stitch.estimate_offset(samples) == pytest.approx(100.0,
                                                            abs=1e-6)
    assert stitch.estimate_offset([]) == 0.0


def test_estimate_offset_negative_and_even_count():
    samples = [(t, t + 0.01, (t + 0.005) - 40.0) for t in (1.0, 2.0)]
    assert stitch.estimate_offset(samples) == pytest.approx(-40.0)


# -------------------------------------------------------------- stitch
def _export(process, spans, clock=0.0):
    return {"process": process, "clock": clock,
            "spans": [list(s) for s in spans], "events_dropped": 0}


def test_stitch_corrects_clocks_and_orders_processes():
    """Two processes whose clocks differ by exactly +100 s: after
    correction the replica's span nests inside the router's request
    window, the router claims the FIRST pid (top lane), and the
    metadata records the applied offsets."""
    router = _export("router", [
        ("router", "req r1", "request", 10.0, 11.0,
         {"trace_id": "t1", "span_id": "root"})])
    replica = _export("replica0", [
        ("replica0", "slot0", "decode", 110.2, 110.9,
         {"trace_id": "t1", "parent_id": "fwd"})])
    out = stitch.stitch([router, replica],
                        offsets={"router": 0.0, "replica0": 100.0})
    assert json.loads(json.dumps(out))
    procs = {e["pid"]: e["args"]["name"]
             for e in out["traceEvents"]
             if e.get("name") == "process_name"}
    assert procs[1] == "router" and procs[2] == "replica0"
    xs = {e["name"]: e for e in out["traceEvents"] if e["ph"] == "X"}
    root, dec = xs["request"], xs["decode"]
    assert root["ts"] == 0.0                       # anchor
    assert root["ts"] <= dec["ts"]
    assert dec["ts"] + dec["dur"] <= root["ts"] + root["dur"]
    assert dec["args"]["parent_id"] == "fwd"       # args untouched
    assert out["metadata"]["clock_offsets_s"]["replica0"] == 100.0
    assert out["metadata"]["processes"] == ["router", "replica0"]


def test_spans_for_trace_and_summarize_fleet():
    router = _export("router", [
        ("router", "req r1", "request", 0.0, 1.0,
         {"trace_id": "t1"}),
        ("router", "req r2", "request", 0.5, 0.9,
         {"trace_id": "t2"})])
    replica = _export("replica0", [
        ("replica0", "slot0", "decode", 0.2, 0.8,
         {"trace_id": "t1"}),
        ("replica0", "scheduler", "decode_step", 0.2, 0.3, None)])
    out = stitch.stitch([router, replica])
    assert {e["args"]["trace_id"]
            for e in stitch.spans_for_trace(out, "t1")} == {"t1"}
    assert len(stitch.spans_for_trace(out, "t1")) == 2
    s = stitch.summarize_fleet(out)
    assert set(s["processes"]) == {"router", "replica0"}
    assert s["processes"]["replica0"]["spans"] == 2
    assert "decode_step" in s["span_names"]
    assert set(s["traces"]) == {"t1", "t2"}
    assert s["traces"]["t1"]["processes"] == ["replica0", "router"]
    assert s["traces"]["t1"]["duration_ms"] == pytest.approx(1000.0)


# ------------------------------------------------- trace_summary --fleet
def test_trace_summary_fleet_mode(tmp_path, capsys):
    """``trace_summary --fleet stitched.json`` summarizes a stitched
    export offline — no TF/xplane dependency, text and --json forms."""
    from distributed_tensorflow_example_tpu.utils.trace_summary import \
        main
    out = stitch.stitch([
        _export("router", [("router", "req r1", "request", 0.0, 1.0,
                            {"trace_id": "t1"})]),
        _export("replica0", [("replica0", "slot0", "decode", 100.3,
                              100.7, {"trace_id": "t1"})]),
    ], offsets={"replica0": 100.0})
    path = tmp_path / "stitched.json"
    path.write_text(json.dumps(out))
    assert main(["--fleet", str(path)]) == 0
    text = capsys.readouterr().out
    assert "process 'router'" in text and "process 'replica0'" in text
    assert "trace t1" in text and "replica0=100.0" in text
    assert main(["--fleet", str(path), "--json"]) == 0
    s = json.loads(capsys.readouterr().out)
    assert s["traces"]["t1"]["spans"] == 2
    assert s["clock_offsets_s"]["replica0"] == 100.0


# ------------------------------------------------------ flight recorder
def test_flightrec_bundle_contents_and_counters(tmp_path,
                                                fresh_recorder):
    """One incident -> one atomically-complete JSON bundle carrying the
    span tail (non-destructive), the registry snapshot, config
    fingerprint, and caller context; the counters ride a NAMESPACED
    registry like the production ones."""
    rec = fresh_recorder
    rec.start()
    rec.add("serving", "slot0", "prefill", 1.0, 2.0, {"request_id": "r"})
    rec.add("other", "lane", "decode", 1.0, 2.0, None)
    reg = Registry(namespace="serving")
    c = reg.counter("serving_incidents_total", "bundles")
    supp = reg.counter("serving_incidents_suppressed_total",
                       "suppressed")
    log_path = tmp_path / "req.jsonl"
    log_path.write_text("line1\nline2\n")
    fr = FlightRecorder(str(tmp_path / "inc"), process="serving",
                        snapshot_fn=reg.snapshot,
                        config={"max_queue": 64},
                        request_log_path=str(log_path),
                        counter=c, suppressed_counter=supp)
    path = fr.incident("watchdog_stall", detail="hb 1.2s",
                       extra={"health": {"status": "stalled"}})
    assert path and os.path.exists(path)
    assert os.path.basename(path).startswith(
        "incident-serving-watchdog_stall-")
    assert not [p for p in os.listdir(tmp_path / "inc")
                if p.endswith(".tmp")]
    with open(path) as f:
        bundle = json.load(f)
    assert bundle["cause"] == "watchdog_stall"
    assert bundle["detail"] == "hb 1.2s"
    assert bundle["health"] == {"status": "stalled"}
    # only THIS process's spans, and non-destructively
    assert [s[2] for s in bundle["spans"]] == ["prefill"]
    assert rec.spans_recorded == 2 and len(rec.drain()) == 2
    assert bundle["config"] == {"max_queue": 64}
    assert bundle["config_fingerprint"] == config_fingerprint(
        {"max_queue": 64})
    assert bundle["request_log_tail"] == ["line1", "line2"]
    # the counter advanced BEFORE the snapshot landed in the bundle,
    # so bundle and live page agree
    assert bundle["registry"]["serving_incidents_total"]["value"] == 1
    assert c.value == 1 and supp.value == 0
    # a same-cause repeat inside the window is suppressed AND counted
    assert fr.incident("watchdog_stall") is None
    assert c.value == 1 and supp.value == 1


def test_flightrec_rate_limit_per_cause_injected_clock(tmp_path):
    now = [0.0]
    fr = FlightRecorder(str(tmp_path), min_interval_s=30.0,
                        clock=lambda: now[0])
    reg = Registry(namespace="router")
    fr._counter = reg.counter("router_incidents_total")
    fr._suppressed = reg.counter("router_incidents_suppressed_total")
    assert fr.incident("watchdog_stall") is not None
    assert fr.incident("watchdog_stall") is None        # suppressed
    assert fr.incident("breaker_open") is not None      # other cause
    now[0] = 31.0
    assert fr.incident("watchdog_stall") is not None    # window over
    names = sorted(os.listdir(tmp_path))
    assert len(names) == 3, names
    assert fr._counter.value == 3 and fr._suppressed.value == 1


def test_flightrec_failed_write_rolls_back_rate_limit(tmp_path):
    """Review regression: a failed bundle write (disk full, unwritable
    dir) must not suppress the cause for min_interval_s — nothing was
    captured, so the NEXT occurrence retries immediately."""
    fr = FlightRecorder(str(tmp_path), min_interval_s=3600.0)
    real_write = fr._write
    boom = [True]

    def flaky_write(*a, **kw):
        if boom[0]:
            boom[0] = False
            raise OSError("disk full")
        return real_write(*a, **kw)

    fr._write = flaky_write
    assert fr.incident("watchdog_stall") is None        # write failed
    path = fr.incident("watchdog_stall")                # retries NOW
    assert path is not None and os.path.exists(path)
    # and the limit applies again after the successful write
    assert fr.incident("watchdog_stall") is None


def test_flightrec_snapshot_failure_degrades_not_raises(tmp_path):
    def bad_snapshot():
        raise RuntimeError("registry gone")

    fr = FlightRecorder(str(tmp_path), snapshot_fn=bad_snapshot)
    path = fr.incident("engine_fatal_rebuild", detail="x")
    with open(path) as f:
        bundle = json.load(f)
    assert "registry" not in bundle
    assert "RuntimeError" in bundle["registry_error"]


def test_flightrec_is_thread_safe_one_bundle_under_racing_probes(
        tmp_path):
    """N concurrent probe threads reporting the same cause: exactly one
    bundle (the production shape — a stalled replica is probed from a
    fast loop)."""
    fr = FlightRecorder(str(tmp_path), min_interval_s=3600.0)
    paths = []

    def probe():
        p = fr.incident("watchdog_stall")
        if p:
            paths.append(p)

    threads = [threading.Thread(target=probe) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(paths) == 1
    assert len(os.listdir(tmp_path)) == 1
