"""Static-shape eval tail + per-step timing records (VERDICT r2 #6, #8).

- evaluate() pads the tail batch to the training batch size and threads a
  ``__valid__`` mask into eval_metrics, so the whole eval pass runs ONE
  compiled executable and the padded rows contribute nothing.
- ``--step_timing`` (ObservabilityConfig.step_timing) records per-dispatch
  wall-time percentiles plus the compiled step's flops/bytes cost analysis
  to the metrics JSONL — the WorkerCacheLogger analogue (SURVEY.md §2.4,
  §5.1: the reference logged per-step RecvTensor start/end usecs).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import (DataConfig,
                                                       MeshShape,
                                                       ObservabilityConfig,
                                                       OptimizerConfig,
                                                       TrainConfig)
from distributed_tensorflow_example_tpu.data.mnist import synthetic_mnist
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
from distributed_tensorflow_example_tpu.train.trainer import Trainer


def _trainer(data, n_eval, *, obs=None, steps=4, spl=1):
    cfg = TrainConfig(
        model="mlp", train_steps=steps, mesh=MeshShape(data=4),
        steps_per_loop=spl,
        data=DataConfig(batch_size=64, seed=3),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        obs=obs or ObservabilityConfig(),
        seed=7)
    model = get_model("mlp", cfg)
    return Trainer(model, cfg,
                   {"x": data["train_x"], "y": data["train_y"]},
                   eval_arrays={"x": data["test_x"][:n_eval],
                                "y": data["test_y"][:n_eval]},
                   mesh=local_mesh(4), process_index=0, num_processes=1)


def _numpy_eval(state, model, xs, ys):
    """Oracle: whole-set metrics in one unpadded forward pass."""
    logits, _ = model.apply(state.params, state.extras,
                            {"x": jnp.asarray(xs), "y": jnp.asarray(ys)})
    logits = np.asarray(logits, np.float32)
    logz = logits - logits.max(-1, keepdims=True)
    logz = logz - np.log(np.exp(logz).sum(-1, keepdims=True))
    loss = -logz[np.arange(len(ys)), ys].mean()
    acc = (logits.argmax(-1) == ys).mean()
    return {"loss": loss, "accuracy": acc}


def test_eval_tail_is_masked_not_dropped_and_single_executable():
    # 150 eval examples @ bs=64 -> batches of 64, 64, and a 22-row tail
    data = synthetic_mnist(num_train=640, num_test=160, seed=0)
    t = _trainer(data, n_eval=150)
    state, _ = t.train()

    got = t.evaluate(state)
    want = _numpy_eval(state, t.model,
                       data["test_x"][:150], data["test_y"][:150])
    assert abs(got["loss"] - want["loss"]) < 1e-4
    assert abs(got["accuracy"] - want["accuracy"]) < 1e-6

    # static-shape discipline: full batches and the padded tail share ONE
    # compiled executable (the old path recompiled per tail shape)
    assert t._eval_fn._cache_size() == 1
    t.close()


def test_eval_exact_multiple_unchanged():
    data = synthetic_mnist(num_train=640, num_test=128, seed=0)
    t = _trainer(data, n_eval=128)
    state, _ = t.train()
    got = t.evaluate(state)
    want = _numpy_eval(state, t.model,
                       data["test_x"][:128], data["test_y"][:128])
    assert abs(got["loss"] - want["loss"]) < 1e-4
    assert abs(got["accuracy"] - want["accuracy"]) < 1e-6
    assert t._eval_fn._cache_size() == 1
    t.close()


def test_bert_eval_tail_masked():
    """The mask composes with BERT's per-token MLM weights."""
    from distributed_tensorflow_example_tpu.models.bert import (Bert,
                                                                BertConfig)
    cfg = BertConfig.tiny()
    cfg.dropout = 0.0
    model = Bert(cfg)
    params = model.init(jax.random.key(0))
    batch = model.dummy_batch(8)

    ref = {k: float(v) for k, v in
           model.eval_metrics(params, {}, batch).items()}

    # pad 8 -> 12 with garbage rows; mask must make them invisible
    padded = {k: np.concatenate([v, v[:4][::-1]]) for k, v in batch.items()}
    padded["__valid__"] = np.array([1.0] * 8 + [0.0] * 4, np.float32)
    got = {k: float(v) for k, v in
           model.eval_metrics(params, {}, padded).items()}
    assert abs(got["loss"] - ref["loss"]) < 1e-5
    assert abs(got["mlm_accuracy"] - ref["mlm_accuracy"]) < 1e-6


def test_step_timing_records(tmp_path):
    metrics_path = str(tmp_path / "metrics.jsonl")
    data = synthetic_mnist(num_train=640, num_test=64, seed=0)
    obs = ObservabilityConfig(log_every_steps=4, metrics_path=metrics_path,
                              step_timing=True)
    t = _trainer(data, n_eval=64, obs=obs, steps=8)
    t.train()
    t.close()

    recs = [json.loads(l) for l in open(metrics_path)]
    timing = [r for r in recs if "step_timing_ms" in r]
    assert timing, f"no step_timing_ms records in {recs}"
    st = timing[0]["step_timing_ms"]
    for key in ("n", "mean", "p50", "p90", "p99", "max",
                "first_dispatch_ms"):
        assert key in st, key
    assert st["n"] >= 1 and st["p99"] >= st["p50"] > 0.0

    # the compiled step's static cost analysis is recorded exactly once
    costs = [r for r in recs if "step_cost_analysis" in r]
    assert len(costs) == 1
    assert costs[0]["step_cost_analysis"].get("flops", 0) > 0


def test_step_timing_with_steps_per_loop(tmp_path):
    """Timing records work for the K-steps-per-dispatch loop too."""
    metrics_path = str(tmp_path / "metrics.jsonl")
    data = synthetic_mnist(num_train=640, num_test=64, seed=0)
    obs = ObservabilityConfig(log_every_steps=4, metrics_path=metrics_path,
                              step_timing=True)
    t = _trainer(data, n_eval=64, obs=obs, steps=16, spl=4)
    t.train()
    t.close()

    recs = [json.loads(l) for l in open(metrics_path)]
    timing = [r for r in recs if "step_timing_ms" in r]
    assert timing
    assert timing[0]["step_timing_ms"]["steps_per_dispatch"] == 4


def test_metrics_stream_opens_with_full_config(tmp_path):
    """Each run SEGMENT of the metrics stream opens with the full
    resolved TrainConfig (flag-print parity): the JSONL appends across
    restarts, so a resumed run writes its own fresh config record."""
    from distributed_tensorflow_example_tpu.cli.train import main
    metrics = tmp_path / "m.jsonl"
    base = ["--model=mlp", "--batch_size=64", "--prng_impl=rbg",
            f"--metrics_path={metrics}", f"--ckpt_dir={tmp_path}/ckpt",
            "--save_steps=10"]
    rc = main(base + ["--train_steps=10", "--learning_rate=0.5"])
    assert rc == 0
    first = json.loads(metrics.read_text().splitlines()[0])
    assert first["config"]["model"] == "mlp"
    assert first["config"]["prng_impl"] == "rbg"
    assert first["config"]["data"]["batch_size"] == 64
    assert first["config"]["optimizer"]["learning_rate"] == 0.5
    assert first["num_processes"] == 1 and first["start_step"] == 0

    # resume with a changed flag: the appended segment opens with ITS
    # config (consumers take the last config record before a step)
    rc = main(base + ["--train_steps=20", "--learning_rate=0.1"])
    assert rc == 0
    configs = [json.loads(l) for l in metrics.read_text().splitlines()
               if "config" in json.loads(l)]
    assert len(configs) == 2
    assert configs[1]["config"]["optimizer"]["learning_rate"] == 0.1
    assert configs[1]["start_step"] == 10


def test_learning_rate_logged_with_rates(tmp_path):
    """The metrics stream carries the LR that actually scaled each
    logged step's gradients (the reference era's learning_rate summary;
    optax evaluates the schedule at the pre-increment count, so step N
    used sched(N-1))."""
    data = synthetic_mnist(256, 64)
    jpath = str(tmp_path / "m.jsonl")
    cfg = TrainConfig(model="mlp", train_steps=4,
                      data=DataConfig(batch_size=64),
                      optimizer=OptimizerConfig(
                          name="sgd", learning_rate=0.5,
                          decay_schedule="polynomial", total_steps=4),
                      obs=ObservabilityConfig(log_every_steps=2,
                                              metrics_path=jpath))
    tr = Trainer(get_model("mlp", cfg), cfg,
                 {"x": data["train_x"], "y": data["train_y"]},
                 mesh=local_mesh(1, {"data": 1}),
                 process_index=0, num_processes=1)
    tr.train()
    tr.close()
    recs = [json.loads(l) for l in open(jpath)]
    lrs = {r["step"]: r["learning_rate"] for r in recs
           if "learning_rate" in r}
    assert lrs, recs
    # polynomial over 4 steps: the step-2 update used sched(1) = 0.375,
    # the step-4 update used sched(3) = 0.125
    assert lrs[2] == pytest.approx(0.375)
    assert lrs[4] == pytest.approx(0.125)
    assert tr.learning_rate_at(1) == pytest.approx(0.5)   # sched(0)


def test_early_stopping_stops_and_validates(tmp_path):
    """stop_if_no_decrease_hook parity: a metric that cannot improve
    (accuracy already saturated at 1.0 on this easy set) trips the
    patience and stops before train_steps; misconfigurations fail
    fast."""
    data = synthetic_mnist(512, 128)
    arrays = {"x": data["train_x"], "y": data["train_y"]}
    evals = {"x": data["test_x"], "y": data["test_y"]}
    cfg = TrainConfig(model="mlp", train_steps=400, eval_every_steps=20,
                      early_stop_metric="accuracy",
                      early_stop_patience=2,
                      data=DataConfig(batch_size=64),
                      optimizer=OptimizerConfig(name="sgd",
                                                learning_rate=0.5))
    tr = Trainer(get_model("mlp", cfg), cfg, arrays, eval_arrays=evals,
                 mesh=local_mesh(1, {"data": 1}),
                 process_index=0, num_processes=1)
    state, summary = tr.train()
    tr.close()
    # accuracy saturates at 1.0 quickly; after 2 non-improving evals the
    # loop stops long before 400
    assert summary["final_step"] < 400, summary["final_step"]

    with pytest.raises(ValueError, match="early_stop"):
        Trainer(get_model("mlp", cfg), cfg.replace(eval_every_steps=0),
                arrays, eval_arrays=evals,
                mesh=local_mesh(1, {"data": 1}),
                process_index=0, num_processes=1)
    with pytest.raises(ValueError, match="early_stop"):
        Trainer(get_model("mlp", cfg),
                cfg.replace(early_stop_patience=0), arrays,
                eval_arrays=evals, mesh=local_mesh(1, {"data": 1}),
                process_index=0, num_processes=1)


def test_early_stop_unknown_metric_raises(tmp_path):
    data = synthetic_mnist(128, 64)
    cfg = TrainConfig(model="mlp", train_steps=4, eval_every_steps=2,
                      early_stop_metric="f1",
                      data=DataConfig(batch_size=64))
    tr = Trainer(get_model("mlp", cfg), cfg,
                 {"x": data["train_x"], "y": data["train_y"]},
                 eval_arrays={"x": data["test_x"], "y": data["test_y"]},
                 mesh=local_mesh(1, {"data": 1}),
                 process_index=0, num_processes=1)
    with pytest.raises(ValueError, match="early_stop_metric"):
        tr.train()
    tr.close()


def test_early_stop_state_survives_resume(tmp_path):
    """Preemption parity: the patience counter persists in a sidecar
    next to the checkpoints, so a resumed run continues the window
    instead of resetting it."""
    data = synthetic_mnist(512, 128)
    arrays = {"x": data["train_x"], "y": data["train_y"]}
    evals = {"x": data["test_x"], "y": data["test_y"]}
    from distributed_tensorflow_example_tpu.config import CheckpointConfig
    cfg = TrainConfig(model="mlp", train_steps=60, eval_every_steps=20,
                      early_stop_metric="accuracy",
                      early_stop_patience=4,
                      data=DataConfig(batch_size=64),
                      optimizer=OptimizerConfig(name="sgd",
                                                learning_rate=0.5),
                      checkpoint=CheckpointConfig(
                          directory=str(tmp_path / "ck"), save_steps=20))
    tr = Trainer(get_model("mlp", cfg), cfg, arrays, eval_arrays=evals,
                 mesh=local_mesh(1, {"data": 1}),
                 process_index=0, num_processes=1)
    tr.train()
    misses1, best1 = tr._early_misses, tr._early_best
    tr.close()
    assert json.load(open(tmp_path / "ck" / "early_stop.json")) \
        == {"best": best1, "misses": misses1}

    # resume for more steps: the counters carry over
    cfg2 = cfg.replace(train_steps=100)
    tr2 = Trainer(get_model("mlp", cfg2), cfg2, arrays,
                  eval_arrays=evals, mesh=local_mesh(1, {"data": 1}),
                  process_index=0, num_processes=1)
    tr2.initialize()
    assert tr2._early_best == best1
    assert tr2._early_misses == misses1
    tr2.close()
