"""Sync-replica semantics tests.

The load-bearing invariant (SURVEY.md §4 item 2, §7 hard-parts item 2): the
N-device sync step must equal the 1-device step on the same global batch —
the promise SyncReplicasOptimizer's docs make for the reference
(sync_replicas_optimizer.py:49-55).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import (OptimizerConfig,
                                                       SyncConfig)
from distributed_tensorflow_example_tpu.models.mlp import MLP
from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
    SyncReplicas)
from distributed_tensorflow_example_tpu.train.optimizers import make_optimizer

BATCH = 32


def _setup(n_dev, mode="auto", accum=1, seed=0):
    model = MLP(in_dim=20, hidden=16, num_classes=4)
    mesh = local_mesh(n_dev)
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
    sync = SyncReplicas(model.loss, tx, mesh,
                        sync=SyncConfig(mode=mode, accum_steps=accum))
    state = sync.init(model.init, seed=seed)
    return model, sync, state


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    return {"x": rs.rand(BATCH, 20).astype(np.float32),
            "y": rs.randint(0, 4, size=(BATCH,), dtype=np.int32)}


def _params_flat(state):
    return jax.tree_util.tree_map(np.asarray, jax.device_get(state.params))


def assert_trees_close(a, b, **kw):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(x, y, **kw), a, b)


def test_loss_matches_numpy_oracle():
    """MLP fwd + softmax-xent against a hand-written numpy computation."""
    model, sync, state = _setup(1)
    batch = _batch()
    loss, (aux, _) = model.loss(
        jax.device_get(state.params), {}, batch, jax.random.key(0))

    p = _params_flat(state)
    h = np.maximum(batch["x"] @ p["fc1"]["kernel"] + p["fc1"]["bias"], 0.0)
    logits = h @ p["fc2"]["kernel"] + p["fc2"]["bias"]
    z = logits - logits.max(axis=1, keepdims=True)
    logp = z - np.log(np.exp(z).sum(axis=1, keepdims=True))
    want = -logp[np.arange(BATCH), batch["y"]].mean()
    np.testing.assert_allclose(float(loss), want, rtol=1e-5)
    acc = (logits.argmax(1) == batch["y"]).mean()
    np.testing.assert_allclose(float(aux["accuracy"]), acc, rtol=1e-6)


@pytest.mark.parametrize("mode", ["auto", "shard_map"])
def test_nchip_step_equals_single_chip(mode):
    """8-device sync step == 1-device big-batch step on the same batch."""
    _, sync1, state1 = _setup(1)
    _, sync8, state8 = _setup(8, mode=mode)
    batch = _batch()

    s1, m1 = sync1.step(state1, sync1.shard_batch(batch))
    s8, m8 = sync8.step(state8, sync8.shard_batch(batch))

    np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]),
                               rtol=1e-5)
    assert_trees_close(_params_flat(s1), _params_flat(s8),
                       rtol=2e-5, atol=1e-6)
    assert int(s8.step) == 1


def test_shard_map_mode_equals_auto_mode():
    _, sync_a, state_a = _setup(8, mode="auto")
    _, sync_s, state_s = _setup(8, mode="shard_map")
    batch = _batch()
    sa, ma = sync_a.step(state_a, sync_a.shard_batch(batch))
    ss, ms = sync_s.step(state_s, sync_s.shard_batch(batch))
    np.testing.assert_allclose(float(ma["loss"]), float(ms["loss"]),
                               rtol=1e-5)
    assert_trees_close(_params_flat(sa), _params_flat(ss),
                       rtol=2e-5, atol=1e-6)


def test_grad_accumulation_matches_full_batch():
    """accum_steps=4 microbatching == single full-batch step (the
    accumulate-N-then-apply residue, module docstring)."""
    _, sync1, state1 = _setup(1, accum=1)
    _, sync4, state4 = _setup(1, accum=4)
    batch = _batch()
    s1, m1 = sync1.step(state1, sync1.shard_batch(batch))
    s4, m4 = sync4.step(state4, sync4.shard_batch(batch))
    np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]),
                               rtol=1e-5)
    assert_trees_close(_params_flat(s1), _params_flat(s4),
                       rtol=2e-5, atol=1e-6)


def test_replicas_to_aggregate_mismatch_rejected():
    model = MLP(in_dim=20, hidden=16, num_classes=4)
    mesh = local_mesh(8)
    tx = make_optimizer(OptimizerConfig())
    with pytest.raises(ValueError, match="replicas_to_aggregate"):
        SyncReplicas(model.loss, tx, mesh,
                     sync=SyncConfig(replicas_to_aggregate=4))


@pytest.mark.parametrize("mode", ["auto", "shard_map"])
def test_multi_step_scan_equals_sequential_steps(mode):
    """multi_step (K steps, one dispatch via lax.scan) == K sequential
    step() calls — the iterations_per_loop correctness contract."""
    K = 4
    _, sync_seq, state_seq = _setup(8, mode=mode)
    _, sync_k, state_k = _setup(8, mode=mode)
    host = [_batch(i) for i in range(K)]

    for b in host:
        state_seq, m_seq = sync_seq.step(state_seq, sync_seq.shard_batch(b))

    stacked = {k: np.stack([b[k] for b in host]) for k in host[0]}
    state_k, m_k = sync_k.multi_step(state_k,
                                     sync_k.shard_stacked_batch(stacked))

    assert int(state_k.step) == K
    np.testing.assert_allclose(float(m_seq["loss"]), float(m_k["loss"]),
                               rtol=1e-5)
    assert_trees_close(_params_flat(state_seq), _params_flat(state_k),
                       rtol=2e-5, atol=1e-6)


def test_multi_step_training_reduces_loss():
    model, sync, state = _setup(8)

    def learnable_batch(seed):
        rs = np.random.RandomState(seed)
        protos = np.random.RandomState(99).rand(4, 20).astype(np.float32)
        y = rs.randint(0, 4, size=(BATCH,)).astype(np.int32)
        x = protos[y] + rs.randn(BATCH, 20).astype(np.float32) * 0.1
        return {"x": x, "y": y}

    losses = []
    for i in range(30):
        state, m = sync.step(state, sync.shard_batch(learnable_batch(i % 4)))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7
    assert int(state.step) == 30


def test_debug_checks_catches_nan_at_the_offending_step():
    """SURVEY.md §5.2: checkify float_checks raise at the step where the
    NaN occurs (not later, not at a hook's convenience)."""
    model = MLP(in_dim=20, hidden=16, num_classes=4)
    mesh = local_mesh(8)
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
    sync = SyncReplicas(model.loss, tx, mesh, debug_checks=True)
    state = sync.init(model.init, seed=0)

    good = _batch(0)
    state, m = sync.step(state, sync.shard_batch(good))   # clean step: fine
    assert np.isfinite(float(m["loss"]))

    bad = {"x": good["x"].copy(), "y": good["y"]}
    bad["x"][0, 0] = np.nan
    with pytest.raises(Exception, match="(?i)nan"):
        sync.step(state, sync.shard_batch(bad))
