"""Raw-text BERT pipeline (data/bert_text.py): WordPiece tokenization
with a LOCAL vocab, document packing, and MLM masking with the custom
vocab's special ids. transformers is the producer dependency (offline,
local vocab file only).
"""

import numpy as np
import pytest

pytest.importorskip("transformers")

from distributed_tensorflow_example_tpu.data.bert_text import (
    get_bert_text_data, tokenize_corpus)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    d = tmp_path_factory.mktemp("corpus")
    vocab = (["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
             + [chr(c) for c in range(ord("a"), ord("z") + 1)]
             + ["##" + chr(c) for c in range(ord("a"), ord("z") + 1)]
             + ["the", "quick", "brown", "fox", "jump", "over",
                "lazy", "dog", "pack", "my", "box", "with", "five",
                "dozen", "liquor", "jug", "##ump"])
    (d / "vocab.txt").write_text("\n".join(vocab))
    docs = []
    rs = np.random.RandomState(0)
    words = ["the", "quick", "brown", "fox", "jumps", "over", "lazy",
             "dog", "pack", "my", "box", "with", "five", "dozen",
             "liquor", "jugs"]
    for _ in range(30):
        docs.append(" ".join(rs.choice(words, size=rs.randint(30, 120))))
    (d / "corpus.txt").write_text("\n\n".join(docs))
    return str(d)


def test_tokenize_and_pack(corpus):
    seqs, ids = tokenize_corpus(corpus + "/corpus.txt",
                                corpus + "/vocab.txt", seq_len=32)
    assert seqs.dtype == np.int32 and seqs.shape[1] == 32
    assert len(seqs) > 10
    # every row: [CLS] ... [SEP] then PAD
    assert (seqs[:, 0] == ids["cls"]).all()
    for row in seqs[:20]:
        sep_at = np.where(row == ids["sep"])[0]
        assert len(sep_at) == 1
        assert (row[sep_at[0] + 1:] == ids["pad"]).all()
    assert seqs.max() < ids["vocab_size"]
    # no [UNK] flood: the vocab covers the corpus words
    assert (seqs == ids["unk"]).mean() < 0.01


def test_text_data_masking_respects_custom_ids(corpus):
    train, test, vocab_size = get_bert_text_data(
        corpus, corpus + "/vocab.txt", seq_len=32, max_predictions=6,
        seed=0)
    _, ids = tokenize_corpus(corpus + "/corpus.txt",
                             corpus + "/vocab.txt", seq_len=32)
    for arrays in (train, test):
        assert arrays["input_ids"].shape[1] == 32
        assert arrays["masked_positions"].shape[1] == 6
        w = arrays["masked_weights"].astype(bool)
        # masked labels are REAL tokens, never specials
        labels = arrays["masked_labels"][w]
        assert not np.isin(labels, [ids["pad"], ids["cls"], ids["sep"],
                                    ids["mask"], ids["unk"]]).any()
        # replacement tokens stay inside the vocab
        assert arrays["input_ids"].max() < vocab_size
        # the mask token actually appears (80% rule)
        assert (arrays["input_ids"] == ids["mask"]).sum() > 0
        # attention mask matches padding
        pads = arrays["input_ids"] == ids["pad"]
        # (masked positions may overwrite non-pad tokens, never pads)
        assert (arrays["attention_mask"][pads] == 0).all()


def test_cli_trains_from_text_corpus(corpus, tmp_path):
    """End-to-end: bert_tiny trains from the raw-text corpus directory
    (vocab.txt auto-detected) with loss decreasing."""
    import json

    from distributed_tensorflow_example_tpu.cli.train import main
    metrics = tmp_path / "m.jsonl"
    rc = main(["--model=bert_tiny", f"--data_dir={corpus}",
               "--seq_len=32", "--train_steps=30", "--batch_size=16",
               "--optimizer=adamw", "--learning_rate=1e-3",
               "--log_every_steps=10", "--summary_every_steps=10",
               f"--metrics_path={metrics}"])
    assert rc == 0
    recs = [json.loads(l) for l in metrics.read_text().splitlines()]
    losses = [r["loss"] for r in recs if "loss" in r and "step" in r]
    assert losses and losses[-1] < losses[0]


def test_vocab_file_is_never_tokenized_as_corpus(corpus):
    """Pointing at the corpus DIRECTORY (which contains vocab.txt) must
    tokenize only the corpus documents — identical output to pointing at
    the corpus file alone."""
    by_dir, _ = tokenize_corpus(corpus, corpus + "/vocab.txt", seq_len=32)
    by_file, _ = tokenize_corpus(corpus + "/corpus.txt",
                                 corpus + "/vocab.txt", seq_len=32)
    np.testing.assert_array_equal(by_dir, by_file)


def test_misplaced_specials_rejected(corpus, tmp_path):
    """[MASK] at the end of the vocab leaves no regular-token range —
    a clear error, not an opaque crash inside masking."""
    lines = open(corpus + "/vocab.txt").read().splitlines()
    bad = tmp_path / "bad"
    bad.mkdir()
    reordered = [l for l in lines if l != "[MASK]"] + ["[MASK]"]
    (bad / "vocab.txt").write_text("\n".join(reordered))
    with pytest.raises(ValueError, match="FRONT"):
        tokenize_corpus(corpus + "/corpus.txt", str(bad / "vocab.txt"),
                        seq_len=32)


def test_pretokenized_npy_takes_precedence_over_text(corpus, tmp_path):
    """A data_dir holding BOTH npy files and vocab.txt trains on the npy
    arrays (no silent pipeline switch)."""
    import os
    import shutil

    from distributed_tensorflow_example_tpu.cli.train import (TrainConfig,
                                                              load_dataset)
    from distributed_tensorflow_example_tpu.config import DataConfig
    from distributed_tensorflow_example_tpu.models import get_model
    d = tmp_path / "both"
    d.mkdir()
    shutil.copy(os.path.join(corpus, "vocab.txt"), d / "vocab.txt")
    shutil.copy(os.path.join(corpus, "corpus.txt"), d / "corpus.txt")
    rs = np.random.RandomState(0)
    toks = rs.randint(110, 999, size=(64, 32)).astype(np.int32)
    np.save(d / "tokens.npy", toks)
    cfg = TrainConfig(model="bert_tiny",
                      data=DataConfig(dataset="bert_tiny",
                                      data_dir=str(d), seq_len=32))
    model = get_model("bert_tiny", cfg)
    tr, te = load_dataset(cfg, model)
    # npy arrays are 64 rows split 95/5 — the text corpus would yield a
    # different count entirely
    assert len(tr["input_ids"]) + len(te["input_ids"]) == 64


def test_cli_vocab_larger_than_model_errors(corpus, tmp_path):
    """A vocab bigger than the model's embedding table must hard-error
    (ids beyond the table clamp silently under jit)."""
    import os
    import shutil

    from distributed_tensorflow_example_tpu.cli.train import main
    big = tmp_path / "bigvocab"
    big.mkdir()
    shutil.copy(os.path.join(corpus, "corpus.txt"), big / "corpus.txt")
    base = open(os.path.join(corpus, "vocab.txt")).read().splitlines()
    extra = [f"tok{i}" for i in range(2000)]      # > bert_tiny's 1000
    (big / "vocab.txt").write_text("\n".join(base + extra))
    with pytest.raises(SystemExit, match="vocab"):
        main(["--model=bert_tiny", f"--data_dir={big}", "--seq_len=32",
              "--train_steps=1", "--batch_size=8"])
