"""Trace-summary tool: capture a real (CPU) jax.profiler trace and reduce
it. The xplane proto comes from the installed TF wheel — an optional,
offline-only dependency; skip cleanly when absent."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")

from distributed_tensorflow_example_tpu.utils.trace_summary import (  # noqa: E402
    _union_ms, format_text, summarize)


def test_union_ms_merges_overlaps():
    assert _union_ms([(0, 1_000_000_000), (500_000_000, 2_000_000_000),
                      (3_000_000_000, 4_000_000_000)]) == pytest.approx(3.0)
    assert _union_ms([]) == 0.0


def test_summarize_real_capture(tmp_path):
    @jax.jit
    def f(x):
        return jnp.tanh(x @ x.T).sum()

    x = jnp.asarray(np.random.RandomState(0).rand(256, 256), jnp.float32)
    f(x).block_until_ready()          # compile outside the capture
    jax.profiler.start_trace(str(tmp_path))
    for _ in range(3):
        f(x).block_until_ready()
    jax.profiler.stop_trace()

    s = summarize(str(tmp_path), top=5)
    assert s, "no device planes parsed"
    dev, rec = next(iter(s.items()))
    assert rec["lines"] and all(l["busy_ms"] >= 0 for l in rec["lines"])
    text = format_text(s)
    assert "busy=" in text and dev in text


def test_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        summarize(str(tmp_path / "nope"))


def test_chrome_trace_export(tmp_path):
    """--chrome output (timeline.py parity): valid trace-event JSON with
    process/thread metadata and complete events Perfetto can load."""
    import json as _json

    from distributed_tensorflow_example_tpu.utils.trace_summary import (
        chrome_trace, main)

    @jax.jit
    def f(x):
        return jnp.tanh(x @ x.T).sum()

    x = jnp.asarray(np.random.RandomState(0).rand(128, 128), jnp.float32)
    f(x).block_until_ready()
    cap = tmp_path / "cap"
    jax.profiler.start_trace(str(cap))
    for _ in range(2):
        f(x).block_until_ready()
    jax.profiler.stop_trace()

    trace = chrome_trace(str(cap))
    evs = trace["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    metas = [e for e in evs if e["ph"] == "M"]
    assert xs and metas
    assert any(m["name"] == "process_name" for m in metas)
    assert any(m["name"] == "thread_name" for m in metas)
    for e in xs[:50]:
        assert e["dur"] > 0 and e["ts"] >= 0 and e["name"]

    # cross-line alignment: offsets are rebased onto each line's absolute
    # timestamp_ns, so lines captured simultaneously must overlap in time
    # (the regression would show disjoint/zero-based lines)
    spans: dict = {}
    for e in xs:
        k = (e["pid"], e["tid"])
        lo, hi = spans.get(k, (float("inf"), 0.0))
        spans[k] = (min(lo, e["ts"]), max(hi, e["ts"] + e["dur"]))
    assert min(lo for lo, _ in spans.values()) < 1e6  # rebase keeps ts small
    if len(spans) >= 2:
        (l0, h0), (l1, h1) = sorted(spans.values())[:2]
        assert max(l0, l1) < min(h0, h1), (spans,)

    # the CLI writes a loadable file and truncation bounds event count
    out = tmp_path / "out.trace.json"
    rc = main([str(cap), "--chrome", str(out),
               "--max_events_per_line", "10"])
    assert rc == 0
    loaded = _json.loads(out.read_text())
    per_line: dict = {}
    for e in loaded["traceEvents"]:
        if e["ph"] == "X":
            per_line.setdefault((e["pid"], e["tid"]), 0)
            per_line[(e["pid"], e["tid"])] += 1
    assert per_line and all(n <= 10 for n in per_line.values())
