"""Trace-summary tool: capture a real (CPU) jax.profiler trace and reduce
it. The xplane proto comes from the installed TF wheel — an optional,
offline-only dependency; skip cleanly when absent."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")

from distributed_tensorflow_example_tpu.utils.trace_summary import (  # noqa: E402
    _union_ms, format_text, summarize)


def test_union_ms_merges_overlaps():
    assert _union_ms([(0, 1_000_000_000), (500_000_000, 2_000_000_000),
                      (3_000_000_000, 4_000_000_000)]) == pytest.approx(3.0)
    assert _union_ms([]) == 0.0


def test_summarize_real_capture(tmp_path):
    @jax.jit
    def f(x):
        return jnp.tanh(x @ x.T).sum()

    x = jnp.asarray(np.random.RandomState(0).rand(256, 256), jnp.float32)
    f(x).block_until_ready()          # compile outside the capture
    jax.profiler.start_trace(str(tmp_path))
    for _ in range(3):
        f(x).block_until_ready()
    jax.profiler.stop_trace()

    s = summarize(str(tmp_path), top=5)
    assert s, "no device planes parsed"
    dev, rec = next(iter(s.items()))
    assert rec["lines"] and all(l["busy_ms"] >= 0 for l in rec["lines"])
    text = format_text(s)
    assert "busy=" in text and dev in text


def test_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        summarize(str(tmp_path / "nope"))
