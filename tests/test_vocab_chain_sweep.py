"""vocab_chain_sweep: analytic model sanity + the fresh-process CPU
smoke grid (the acceptance path: the sweep runs end-to-end on CPU)."""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(ROOT, "experiments", "vocab_chain_sweep.py")
sys.path.insert(0, os.path.join(ROOT, "experiments"))

import vocab_chain_sweep as vcs  # noqa: E402


def test_roofline_rows_are_consistent():
    """The analytic model's invariants: fused/chunked pay the recompute
    FLOPs (8nhv vs full's 6nhv); fused's peak logits residency is the
    block tile, orders of magnitude under full's [N, V]; the chunked
    table re-stream grows with S/chunk."""
    b, s = 32, 512
    full = vcs.roofline_row("full", b, s, 0)
    chunked = vcs.roofline_row("chunked", b, s, 512)
    fused = vcs.roofline_row("fused", b, s, 2048)
    assert chunked["chain_TF"] == fused["chain_TF"] > full["chain_TF"]
    assert fused["peak_logits_MiB"] < full["peak_logits_MiB"] / 5
    assert full["peak_logits_MiB"] == pytest.approx(
        32 * 512 * 30522 * 4 / 2**20, rel=1e-4)   # rows round to 2dp
    # chunked at long S re-streams the table per chunk
    long_chunked = vcs.roofline_row("chunked", 4, 4096, 512)
    assert long_chunked["table_GB"] > chunked["table_GB"]
    # every committed grid cell produces a valid row
    for bb, ss in vcs.SHAPES:
        for impl, size in [("full", 0), ("chunked", vcs.CHUNK)] + [
                ("fused", blk) for blk in vcs.BLOCKS]:
            row = vcs.roofline_row(impl, bb, ss, size)
            assert row["mxu_floor_ms"] > 0 and row["hbm_floor_ms"] > 0


def test_roofline_mode_prints_json_lines(capsys):
    vcs.roofline()
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")]
    assert len(lines) == len(vcs.SHAPES) * (2 + len(vcs.BLOCKS))
    for ln in lines:
        json.loads(ln)


@pytest.mark.slow   # fresh-process cells: one compile per cell on CPU
def test_smoke_grid_runs_end_to_end_on_cpu():
    """`--smoke` (the CI path): every impl — full, chunked, fused incl.
    a vocab-not-divisible block — runs a real train step in a fresh
    process and emits the JSON cell contract with a finite loss."""
    out = subprocess.run(
        [sys.executable, SCRIPT, "--smoke"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 4, (out.stdout, out.stderr)
    cells = [json.loads(ln) for ln in lines]
    impls = [(c["impl"], c["size"]) for c in cells]
    assert impls == [("full", None), ("chunked", 32),
                     ("fused", 128), ("fused", 200)]
    for c in cells:
        assert "error" not in c, c
        assert c["loss_finite"] and c["step_ms"] > 0, c
