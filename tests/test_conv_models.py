"""LeNet / ResNet model tests: shapes, BN extras plumbing, sync training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import (OptimizerConfig,
                                                       SyncConfig,
                                                       TrainConfig)
from distributed_tensorflow_example_tpu.models import get_model, list_models
from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
    SyncReplicas)
from distributed_tensorflow_example_tpu.train.optimizers import make_optimizer


def test_registry_has_conv_family():
    assert {"mlp", "lenet", "resnet20", "resnet50"} <= set(list_models())


def test_lenet_forward_shapes():
    m = get_model("lenet")
    params = m.init(jax.random.key(0))
    batch = m.dummy_batch(4)
    logits, _ = m.apply(params, {}, batch)
    assert logits.shape == (4, 10)
    # flat-784 input also accepted (MNIST loader compatibility)
    flat = {"x": batch["x"].reshape(4, 784), "y": batch["y"]}
    logits2, _ = m.apply(params, {}, flat)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(logits2),
                               rtol=1e-5)


def test_resnet20_forward_and_bn_extras():
    m = get_model("resnet20")
    params, extras = m.init(jax.random.key(0))
    batch = m.dummy_batch(4)
    # train mode returns UPDATED extras
    logits, new_extras = m.apply(params, extras, batch, train=True)
    assert logits.shape == (4, 10)
    stem0 = np.asarray(extras["stem_bn"]["mean"])
    stem1 = np.asarray(new_extras["stem_bn"]["mean"])
    assert not np.allclose(stem0, stem1), "BN running mean must move"
    # eval mode leaves extras untouched
    _, same = m.apply(params, new_extras, batch, train=False)
    assert same is new_extras


def test_resnet20_sync_training_step(cpu8):
    cfg = TrainConfig(model="resnet20")
    m = get_model("resnet20", cfg)
    mesh = local_mesh(8)
    tx = make_optimizer(OptimizerConfig(name="momentum", learning_rate=0.01))
    sync = SyncReplicas(m.loss, tx, mesh)
    state = sync.init(m.init, seed=0)
    batch = sync.shard_batch(m.dummy_batch(16))
    state, metrics = sync.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state.step) == 1
    # extras updated through the step
    assert state.extras  # non-empty for BN models


def test_resnet50_compiles_tiny():
    """ResNet-50 is big; assert the abstract init + a lowered forward only
    (full compile on CPU is slow)."""
    m = get_model("resnet50")
    abstract = jax.eval_shape(lambda: m.init(jax.random.key(0)))
    params_shapes, extras_shapes = abstract
    n_params = sum(int(np.prod(x.shape))
                   for x in jax.tree_util.tree_leaves(params_shapes))
    # canonical ResNet-50: ~25.5M params
    assert 25_000_000 < n_params < 26_000_000, n_params
    batch = m.dummy_batch(2)
    out = jax.eval_shape(
        lambda p, e: m.apply(p, e, batch, train=False)[0],
        params_shapes, extras_shapes)
    assert out.shape == (2, 1000)


@pytest.mark.parametrize("name", ["lenet", "resnet20"])
def test_bf16_grad_step_runs(name):
    """Regression: the conv VJP failed with mixed bf16/f32 dtypes when conv
    used preferred_element_type (caught only by a real backward pass)."""
    cfg = TrainConfig(model=name, dtype="bfloat16")
    m = get_model(name, cfg)
    mesh = local_mesh(1)
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.01))
    sync = SyncReplicas(m.loss, tx, mesh)
    state = sync.init(m.init, seed=0)
    state, metrics = sync.step(state, sync.shard_batch(m.dummy_batch(8)))
    assert np.isfinite(float(metrics["loss"]))


def test_lenet_learns(cpu8):
    cfg = TrainConfig(model="lenet")
    m = get_model("lenet", cfg)
    mesh = local_mesh(8)
    tx = make_optimizer(OptimizerConfig(name="momentum", learning_rate=0.05))
    sync = SyncReplicas(m.loss, tx, mesh)
    state = sync.init(m.init, seed=0)

    from distributed_tensorflow_example_tpu.data.mnist import synthetic_mnist
    d = synthetic_mnist(num_train=512, num_test=64)
    x = d["train_x"].reshape(-1, 28, 28, 1)
    losses = []
    for i in range(12):
        lo = (i % 4) * 128
        b = sync.shard_batch({"x": x[lo:lo + 128],
                              "y": d["train_y"][lo:lo + 128]})
        state, metr = sync.step(state, b)
        losses.append(float(metr["loss"]))
    assert losses[-1] < losses[0]


def test_topk_accuracy_oracle():
    """topk_accuracy vs a numpy argsort oracle, incl. the padded-tail
    mask."""
    from distributed_tensorflow_example_tpu.ops.losses import (
        accuracy, topk_accuracy)
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(32, 10).astype(np.float32))
    labels = jnp.asarray(rs.randint(0, 10, 32).astype(np.int32))
    top5 = np.argsort(np.asarray(logits), axis=-1)[:, -5:]
    want = np.mean([int(labels[i]) in top5[i] for i in range(32)])
    got = float(topk_accuracy(logits, labels, 5))
    assert got == pytest.approx(want)
    # k=1 degenerates to plain accuracy
    assert float(topk_accuracy(logits, labels, 1)) == pytest.approx(
        float(accuracy(logits, labels)))
    # masked: only the first 8 rows count
    w = jnp.asarray(([1.0] * 8) + ([0.0] * 24))
    want8 = np.mean([int(labels[i]) in top5[i] for i in range(8)])
    assert float(topk_accuracy(logits, labels, 5, where=w)) == \
        pytest.approx(want8)


def test_resnet50_eval_reports_top5():
    cfg = TrainConfig(model="resnet50")
    m = get_model("resnet50", cfg)
    out = m.init(jax.random.key(0))
    params, extras = out if isinstance(out, tuple) else (out, {})
    metrics = jax.jit(m.eval_metrics)(params, extras, m.dummy_batch(4))
    assert "top5_accuracy" in metrics
    assert 0.0 <= float(metrics["top5_accuracy"]) <= 1.0
    # cifar-scale resnet20 (10 classes) also reports it; mlp does not
    m20 = get_model("resnet20", TrainConfig(model="resnet20"))
    out = m20.init(jax.random.key(0))
    p20, e20 = out if isinstance(out, tuple) else (out, {})
    assert "top5_accuracy" in jax.jit(m20.eval_metrics)(
        p20, e20, m20.dummy_batch(4))


def test_bn_stats_dtype_knob(cpu8):
    """--bn_stats_dtype bfloat16 (the ResNet byte-roofline experiment,
    VERDICT r3 task #4): the knob reaches the BN batch-statistic
    reduction, training still converges on CIFAR-scale ResNet-20, and
    running stats stay f32. Invalid values are a hard error."""
    import pytest as _pytest
    cfg = TrainConfig(model="resnet20", bn_stats_dtype="bfloat16")
    m = get_model("resnet20", cfg)
    import jax.numpy as jnp
    assert m.bn_stats_dtype == jnp.bfloat16
    mesh = local_mesh(8)
    tx = make_optimizer(OptimizerConfig(name="momentum", learning_rate=0.05))
    sync = SyncReplicas(m.loss, tx, mesh)
    state = sync.init(m.init, seed=0)
    batch = sync.shard_batch(m.dummy_batch(64))
    losses = []
    for _ in range(8):
        state, metrics = sync.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    # running stats accumulate in f32 regardless of the reduction dtype
    for leaf in jax.tree_util.tree_leaves(state.extras):
        assert leaf.dtype == np.float32, leaf.dtype
    with _pytest.raises(ValueError, match="bn_stats_dtype"):
        get_model("resnet20", TrainConfig(model="resnet20",
                                          bn_stats_dtype="float16"))
