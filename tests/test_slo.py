"""SLO attainment & goodput observability (round 19, DESIGN.md §22).

- pure burn-rate math (obs/slo.py): spec grammar, the three SLI
  kinds, the multi-window breach rule — all on fabricated histories,
  zero sleeps;
- engine-level terminal-outcome accounting: every outcome (ok, shed,
  expired, cancelled) feeds the per-class serving_slo_* counters
  EXACTLY once, goodput counts only deadline-met tokens, and the
  request-log JSONL event carries the round-19 schema (priority /
  deadline_ms / outcome / slo_good — the satellite completeness fix);
- serving_http: GET /stats/history (forced sample + ring), the
  /healthz advisory slo block, and the deterministic slo_burn
  incident path (breach -> exactly one rate-limited bundle);
- serving_router: the fleet /stats/history rollup over fake replicas
  with a known clock offset.
"""

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "experiments"))
sys.path.insert(0, ROOT)

import serving_load  # noqa: E402

from distributed_tensorflow_example_tpu.obs import slo as obs_slo  # noqa: E402
from distributed_tensorflow_example_tpu.obs.registry import Registry  # noqa: E402
from distributed_tensorflow_example_tpu.serving import load_stepwise  # noqa: E402
from distributed_tensorflow_example_tpu.serving_batch import (  # noqa: E402
    DeadlineExceededError, GenerationEngine, RequestCancelledError,
    ShedError)
from distributed_tensorflow_example_tpu.serving_http import (  # noqa: E402
    PredictServer)
from distributed_tensorflow_example_tpu.serving_router import (  # noqa: E402
    Replica, ReplicaRouter)
from distributed_tensorflow_example_tpu.utils.metrics import (  # noqa: E402
    MetricsLogger)

PROMPT_LEN = 12
MAX_NEW = 6
SLOTS = 3
BLOCK = 4


@pytest.fixture(scope="module")
def export_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("slo_obs"))
    vocab = serving_load.build_export(
        d, prompt_len=PROMPT_LEN, max_new=MAX_NEW, slots=SLOTS,
        seed=0, paged=True, block_size=BLOCK)
    return d, vocab


def _prompt(vocab, n=5, seed=0):
    rs = np.random.RandomState(seed)
    return rs.randint(0, vocab, (n,)).astype(np.int32)


# ---------------------------------------------------------------------------
# pure math: spec grammar
# ---------------------------------------------------------------------------

def test_parse_slo_spec_grammar():
    objs = obs_slo.parse_slo_spec(
        "interactive:p95_ms=250@0.9;interactive:hit_rate=0.99;"
        "all:availability=0.999")
    assert [o.key() for o in objs] == [
        "interactive:p95_ms", "interactive:hit_rate",
        "all:availability"]
    assert objs[0].target == 250.0 and objs[0].goal == 0.9
    assert objs[1].goal == 0.99
    # p95 goal defaults to 0.95
    assert obs_slo.parse_slo_spec("batch:p95_ms=100")[0].goal == 0.95


@pytest.mark.parametrize("bad,match", [
    ("interactive:p95_ms", "expected"),
    ("p95_ms=250", "class:kind"),
    ("interactive:nope=0.9", "kind"),
    ("wrong:hit_rate=0.9", "class"),
    ("interactive:hit_rate=0.9@0.8", "no @goal"),
    ("interactive:hit_rate=1.5", "goal"),
    ("interactive:p95_ms=0@0.9", "target"),
    ("best_effort:availability=0.9", "all"),
    ("interactive:hit_rate=0.9;interactive:hit_rate=0.8", "repeats"),
    (";;", "no objectives"),
])
def test_parse_slo_spec_rejects(bad, match):
    with pytest.raises(ValueError, match=match):
        obs_slo.parse_slo_spec(bad)


def test_default_objectives_are_valid():
    objs = obs_slo.default_objectives()
    assert len(objs) == 3
    assert {o.kind for o in objs} == {"p95_ms", "hit_rate",
                                      "availability"}


# ---------------------------------------------------------------------------
# pure math: SLIs and multi-window burn
# ---------------------------------------------------------------------------

def _snap(cls="interactive", served=0, good=0, failed=0, lat=()):
    reg = Registry()
    reg.counter(f"serving_slo_served_{cls}_total").inc(served)
    reg.counter(f"serving_slo_good_{cls}_total").inc(good)
    reg.counter("serving_slo_served_total").inc(served)
    reg.counter("serving_slo_good_total").inc(good)
    reg.counter("serving_requests_failed_total").inc(failed)
    h = reg.histogram(f"serving_latency_{cls}_seconds",
                      buckets=(0.1, 1.0))
    for v in lat:
        h.observe(v)
    return reg.snapshot()


def test_sli_hit_rate_and_burn():
    hist = [(0.0, _snap()), (60.0, _snap(served=20, good=18))]
    obj = obs_slo.Objective("interactive", "hit_rate", 0.95, 0.95)
    good, total = obs_slo.sli(hist, obj)
    assert (good, total) == (18.0, 20.0)
    # err 0.1 over budget 0.05 -> burn 2.0
    assert obs_slo.burn_rate(good, total, 0.95) == pytest.approx(2.0)
    assert obs_slo.burn_rate(0, 0, 0.95) == 0.0      # idle: no burn


def test_sli_availability_and_p95():
    hist = [(0.0, _snap()),
            (60.0, _snap(served=10, good=10, failed=2,
                         lat=[0.05] * 8 + [0.5] * 2))]
    avail = obs_slo.Objective("all", "availability", 0.999, 0.999)
    good, total = obs_slo.sli(hist, avail)
    assert (good, total) == (8.0, 10.0)
    p95 = obs_slo.Objective("interactive", "p95_ms", 100.0, 0.9)
    good, total = obs_slo.sli(hist, p95)
    assert total == 10.0
    assert good == pytest.approx(8.0)     # the 100ms bound = bucket 0.1
    # empty window -> (0, 0)
    assert obs_slo.sli(hist[-1:], p95) == (0.0, 0.0)


def test_evaluate_multi_window_breach_rule():
    """Breach needs BOTH windows burning: a long-quiet history with
    one recent bad burst trips the fast window only (slow window
    dilutes it below threshold) -> no breach; sustained errors trip
    both -> breach; a recovered incident (errors old, fast window
    clean) -> no breach."""
    obj = [obs_slo.Objective("interactive", "hit_rate", 0.9, 0.9)]

    def ev(hist, now):
        return obs_slo.evaluate(hist, obj, now=now, fast_s=60.0,
                                slow_s=600.0, threshold=2.0)[0]

    # sustained: every request bad in both windows
    sustained = [(0.0, _snap()),
                 (550.0, _snap(served=50, good=25)),
                 (600.0, _snap(served=100, good=50))]
    r = ev(sustained, 600.0)
    assert r["burn_fast"] == pytest.approx(5.0)
    assert r["burn_slow"] == pytest.approx(5.0)
    assert r["breach"] and r["attainment"] == pytest.approx(0.5)
    # recent-burst-only: slow window dilutes below threshold
    burst = [(0.0, _snap()),
             (540.0, _snap(served=1000, good=1000)),
             (600.0, _snap(served=1010, good=1005))]
    r = ev(burst, 600.0)
    assert r["burn_fast"] == pytest.approx(5.0)
    assert r["burn_slow"] < 2.0
    assert not r["breach"]
    # recovered: errors outside the fast window
    recovered = [(0.0, _snap()),
                 (500.0, _snap(served=100, good=50)),
                 (599.0, _snap(served=100, good=50)),
                 (600.0, _snap(served=100, good=50))]
    r = ev(recovered, 600.0)
    assert r["burn_fast"] == 0.0 and r["burn_slow"] > 2.0
    assert not r["breach"]


def test_summarize_names_breaching_and_worst():
    results = obs_slo.evaluate(
        [(0.0, _snap()), (10.0, _snap(served=10, good=0))],
        [obs_slo.Objective("interactive", "hit_rate", 0.9, 0.9),
         obs_slo.Objective("all", "availability", 0.999, 0.999)],
        fast_s=60.0, slow_s=60.0, threshold=2.0)
    s = obs_slo.summarize(results)
    assert s["objectives"] == 2
    assert "interactive:hit_rate" in s["breaching"]
    assert s["worst_burn"]["burn_fast"] >= 10.0
    assert obs_slo.summarize([]) == {
        "objectives": 0, "breaching": [], "worst_burn": None}


# ---------------------------------------------------------------------------
# engine: terminal-outcome accounting + request-log schema
# ---------------------------------------------------------------------------

def test_engine_counts_every_class_and_goodput(export_dir):
    """One retired request per priority class: served == good per
    class, per-class latency histograms observe, and goodput counts
    exactly the emitted tokens (no deadlines -> every token good)."""
    d, vocab = export_dir
    eng = GenerationEngine(load_stepwise(d)).start()
    try:
        handles = [eng.submit(_prompt(vocab, seed=i), max_new=3,
                              priority=cls)
                   for i, cls in enumerate(
                       ("interactive", "batch", "best_effort"))]
        for h in handles:
            h.result(timeout=120)
        snap = eng.metrics_snapshot()
    finally:
        eng.close()
    for cls in ("interactive", "batch", "best_effort"):
        assert snap[f"serving_slo_served_{cls}_total"]["value"] == 1
        assert snap[f"serving_slo_good_{cls}_total"]["value"] == 1
        assert snap[f"serving_latency_{cls}_seconds"]["count"] == 1
    assert snap["serving_slo_served_total"]["value"] == 3
    assert snap["serving_slo_good_total"]["value"] == 3
    assert snap["serving_goodput_tokens_total"]["value"] \
        == snap["serving_tokens_out_total"]["value"] == 9


def test_request_log_schema_across_outcomes(export_dir, tmp_path):
    """The satellite fix pinned: every JSONL event — ok AND the
    failure outcomes that predate it — carries request_id, priority,
    deadline_ms, outcome, slo_good, tokens, total_ms; ok events keep
    the full phase breakdown."""
    d, vocab = export_dir
    log_path = str(tmp_path / "req.jsonl")
    logger = MetricsLogger(log_path)
    # shed_policy off: the feasibility rule would SHED the 1ms-
    # deadline request before it could expire (correct behavior —
    # PR 14 — but this test needs the expiry outcome)
    eng = GenerationEngine(load_stepwise(d), shed_policy="off",
                           metrics_logger=logger).start()
    try:
        # ok
        eng.submit(_prompt(vocab), max_new=2,
                   priority="batch").result(timeout=120)
        # expired: a 1ms deadline the scheduler sweeps between steps
        with pytest.raises(DeadlineExceededError):
            eng.submit(_prompt(vocab, seed=1), max_new=MAX_NEW,
                       deadline_ms=1).result(timeout=120)
    finally:
        eng.close()
        logger.close()
    # shed + cancelled: queued-path outcomes on an UNSTARTED engine
    # (no scheduler race — the queue holds them until we act)
    logger2 = MetricsLogger(log_path)
    eng2 = GenerationEngine(load_stepwise(d), metrics_logger=logger2)
    try:
        h_shed = eng2.submit(_prompt(vocab, seed=2), max_new=2,
                             priority="best_effort")
        h_cans = eng2.submit(_prompt(vocab, seed=3), max_new=2,
                             request_id="cancel-me")
        eng2._shed_queued(
            lambda r: r.request_id == h_shed.request_id,
            reason="test shed")
        assert eng2.cancel("cancel-me")
        with pytest.raises(ShedError):
            h_shed.result(timeout=5)
        with pytest.raises(RequestCancelledError):
            h_cans.result(timeout=5)
        snap = eng2.metrics_snapshot()
        assert snap["serving_slo_served_best_effort_total"][
            "value"] == 1
        assert snap["serving_slo_good_best_effort_total"][
            "value"] == 0
    finally:
        eng2.close()
        logger2.close()
    events = [json.loads(ln) for ln in open(log_path)]
    events = [e for e in events if e.get("event") == "generate"]
    by_outcome = {e["outcome"]: e for e in events}
    assert set(by_outcome) == {"ok", "expired", "shed", "cancelled"}
    for e in events:
        for key in ("request_id", "priority", "deadline_ms",
                    "outcome", "slo_good", "tokens", "total_ms"):
            assert key in e, (e["outcome"], key)
    ok = by_outcome["ok"]
    assert ok["priority"] == "batch" and ok["slo_good"] is True
    assert ok["tokens"] == 2
    for key in ("queue_ms", "prefill_ms", "decode_ms"):
        assert key in ok
    assert by_outcome["expired"]["deadline_ms"] == 1
    assert by_outcome["expired"]["slo_good"] is False
    assert by_outcome["shed"]["priority"] == "best_effort"
    assert by_outcome["cancelled"]["request_id"] == "cancel-me"


def test_goodput_excludes_deadline_missed_tokens(export_dir):
    """A request that retires past its deadline is served-not-good:
    its tokens stay OUT of serving_goodput_tokens_total while
    serving_tokens_out_total keeps counting them."""
    d, vocab = export_dir
    eng = GenerationEngine(load_stepwise(d),
                           shed_policy="off").start()
    try:
        eng.submit(_prompt(vocab), max_new=2).result(timeout=120)
        with pytest.raises(DeadlineExceededError):
            eng.submit(_prompt(vocab, seed=1), max_new=MAX_NEW,
                       deadline_ms=1).result(timeout=120)
        snap = eng.metrics_snapshot()
    finally:
        eng.close()
    assert snap["serving_slo_served_total"]["value"] == 2
    assert snap["serving_slo_good_total"]["value"] == 1
    assert snap["serving_goodput_tokens_total"]["value"] == 2
    assert snap["serving_tokens_out_total"]["value"] >= 2


# ---------------------------------------------------------------------------
# serving_http: /stats/history, /healthz advisory, slo_burn incident
# ---------------------------------------------------------------------------

def _get(port, path):
    import urllib.request
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return json.loads(r.read())


def test_stats_history_off_by_default(export_dir):
    d, _ = export_dir
    with PredictServer(d) as srv:
        body = _get(srv.port, "/stats/history")
        assert body["enabled"] is False and body["samples"] == []
        assert "slo" not in _get(srv.port, "/healthz")


def test_slo_spec_requires_history_sampler(export_dir):
    d, _ = export_dir
    with pytest.raises(ValueError, match="history_interval_s"):
        PredictServer(d, slo_spec="interactive:hit_rate=0.9")
    with pytest.raises(ValueError, match="history_interval_s"):
        PredictServer(d, history_interval_s=-1.0)


def test_p95_target_beyond_bucket_coverage_refused(export_dir):
    """A p95_ms target past the latency histograms' largest finite
    bucket (60 s) is unmeasurable — +Inf-bucket observations cannot be
    classified against it, and the pessimistic count would page
    spurious breaches forever. Arm time refuses it loudly."""
    d, _ = export_dir
    with pytest.raises(ValueError, match="finite bucket"):
        PredictServer(d, history_interval_s=3600.0,
                      slo_spec="interactive:p95_ms=120000@0.9")
    # at the bound is fine (scheduler off = no engine thread; close
    # the never-served listener socket directly — shutdown() would
    # hang without a running serve_forever, the round-15 lesson)
    srv = PredictServer(d, scheduler="off",
                        history_interval_s=3600.0,
                        slo_spec="interactive:p95_ms=60000@0.9")
    srv._httpd.server_close()


def test_history_endpoint_healthz_advisory_and_slo_burn(export_dir,
                                                        tmp_path):
    """The deterministic burn story end-to-end: baseline sample at
    start(), one expired request (err=1 against a 0.9 goal -> burn 10
    over both windows), first poll writes exactly one slo_burn bundle
    (snapshot consistent with the registry), second poll is
    rate-limit suppressed, /healthz carries the advisory block but
    STAYS 200-worthy (status live)."""
    d, vocab = export_dir
    inc_dir = str(tmp_path / "incidents")
    with PredictServer(
            d, incident_dir=inc_dir, shed_policy="off",
            history_interval_s=3600.0, history_samples=32,
            slo_spec="interactive:hit_rate=0.9",
            slo_fast_window_s=7200.0, slo_slow_window_s=7200.0,
            slo_burn_threshold=2.0) as srv:
        deadline = time.monotonic() + 5.0
        while len(srv._sampler) < 1 and time.monotonic() < deadline:
            time.sleep(0.005)               # start()'s baseline capture
        import urllib.error
        import urllib.request
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/models/{srv.name}"
            ":generate",
            data=json.dumps({
                "inputs": {"input_ids":
                           [_prompt(vocab).tolist()]},
                "max_new": MAX_NEW, "deadline_ms": 1}).encode())
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 504
        ei.value.read()
        body = _get(srv.port, "/stats/history")     # poll 1: breach
        assert body["enabled"] is True
        assert len(body["samples"]) >= 2
        results = body["slo"]["results"]
        assert [r["class"] for r in results] == ["interactive"]
        assert results[0]["breach"] is True
        assert results[0]["attainment"] == 0.0
        bundles = [b for b in os.listdir(inc_dir)
                   if "-slo_burn-" in b]
        assert len(bundles) == 1
        with open(os.path.join(inc_dir, bundles[0])) as f:
            bundle = json.load(f)
        assert bundle["cause"] == "slo_burn"
        assert bundle["slo"][0]["breach"] is True
        assert bundle["history_tail"]
        # the embedded registry snapshot is the same atomic read the
        # live page renders: the SLO counters must agree exactly
        reg = bundle["registry"]
        assert reg["serving_slo_served_interactive_total"][
            "value"] == 1
        assert reg["serving_slo_good_interactive_total"]["value"] == 0
        assert reg["serving_incidents_total"]["value"] == 1
        # poll 2: still breaching, suppressed by the per-cause limit
        _get(srv.port, "/stats/history")
        assert len([b for b in os.listdir(inc_dir)
                    if "-slo_burn-" in b]) == 1
        # polls are EPHEMERAL: two polls later the ring still holds
        # only the start() baseline — pollers cannot erode the
        # coverage the burn windows were sized for
        assert len(srv._sampler) == 1
        h = _get(srv.port, "/healthz")
        assert h["status"] == "live"
        assert h["slo"]["breaching"] == ["interactive:hit_rate"]
        assert h["slo"]["worst_burn"]["burn_fast"] >= 2.0
        snap = srv._metrics_snapshot()
        assert snap["serving_incidents_suppressed_total"]["value"] \
            >= 1


# ---------------------------------------------------------------------------
# router: the fleet rollup
# ---------------------------------------------------------------------------

class _FakeReplica:
    """A canned /healthz + /stats/history endpoint whose history sits
    in a clock running OFFSET seconds ahead of the router's — the
    rollup must correct it back."""

    def __init__(self, served, offset=0.0):
        fake = self
        self.offset = float(offset)

        def snap(n):
            reg = Registry()
            reg.counter("serving_slo_served_total").inc(n)
            reg.counter("serving_slo_good_total").inc(n)
            return reg.snapshot()

        # sample stamps sit at BIN CENTERS of the 10s rollup grid (in
        # the router's clock), so a millisecond of offset-estimate
        # error can never push a sample across a bin boundary and
        # flake the alignment assertion
        base = time.perf_counter()
        center = (int(base // 10) + 2) * 10.0 + 5.0
        self.samples = [
            [center + self.offset - 10.0, snap(0)],
            [center + self.offset, snap(served)]]

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/healthz":
                    body = json.dumps({
                        "status": "live", "draining": False,
                        "mono_now": time.perf_counter()
                        + fake.offset}).encode()
                elif self.path == "/stats/history":
                    body = json.dumps({
                        "enabled": True, "process": "serving",
                        "interval_s": 10.0,
                        "clock": time.perf_counter() + fake.offset,
                        "samples": fake.samples}).encode()
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()


def test_router_history_rollup_aligns_clocks_and_merges():
    a, b = _FakeReplica(served=3), _FakeReplica(served=5,
                                                offset=500.0)
    router = ReplicaRouter(
        [Replica(f"http://127.0.0.1:{a.port}", name="replica0"),
         Replica(f"http://127.0.0.1:{b.port}", name="replica1")],
        probe_interval_s=0.05).start()
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if len(router.clock_samples().get("replica1", ())) >= 3:
                break
            time.sleep(0.02)
        out = router.stats_history()
    finally:
        router.close()
        a.close()
        b.close()
    assert out["enabled"] is True and out["process"] == "router"
    # replica1's ~500s skew is estimated off the probe stamps and
    # corrected: both replicas' samples land in the same bins
    assert out["clock_offsets_s"]["replica1"] == pytest.approx(
        500.0, abs=1.0)
    assert out["clock_offsets_s"]["replica0"] == pytest.approx(
        0.0, abs=1.0)
    merged = out["samples"]
    assert len(merged) == 2
    assert [s["serving_slo_served_total"]["value"]
            for _, s in merged] == [0, 8]
    # per-replica payloads ride beside the merge, timestamps already
    # corrected into the router clock
    r1 = out["replicas"]["replica1"]
    assert r1["clock_offset_s"] == pytest.approx(500.0, abs=1.0)
    t_corr = r1["samples"][-1][0]
    t_raw = time.perf_counter() + 500.0
    assert abs(t_raw - t_corr) > 400.0      # correction actually applied


def test_router_history_survives_dead_replica():
    a = _FakeReplica(served=2)
    router = ReplicaRouter(
        [Replica(f"http://127.0.0.1:{a.port}", name="replica0"),
         Replica("http://127.0.0.1:1", name="replica1")],
        probe_interval_s=0.05, dead_after_probes=1).start()
    try:
        out = router.stats_history()
    finally:
        router.close()
        a.close()
    assert out["enabled"] is True
    assert "error" in out["replicas"]["replica1"]
    assert [s["serving_slo_served_total"]["value"]
            for _, s in out["samples"]] == [0, 2]
