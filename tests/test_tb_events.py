"""TensorBoard event writer: dependency-free wire format, verified against
TensorFlow's own reader as an oracle (TF is a test-only dependency)."""

import glob

import numpy as np
import pytest

from distributed_tensorflow_example_tpu.data.tfrecord import crc32c
from distributed_tensorflow_example_tpu.utils.metrics import MetricsLogger
from distributed_tensorflow_example_tpu.utils.tb_events import (
    EventFileWriter, _masked_crc)


def test_crc32c_known_vectors():
    # RFC 3720 test vectors (one shared CRC impl with data/tfrecord.py)
    assert crc32c(b"") == 0x0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(bytes(32)) == 0x8A9136AA
    assert _masked_crc(b"123456789") != crc32c(b"123456789")


def test_roundtrip_against_tensorflow_reader(tmp_path):
    tf = pytest.importorskip("tensorflow")

    w = EventFileWriter(str(tmp_path))
    w.scalars(5, {"loss": 0.25, "accuracy": 0.875}, wall_time=123.5)
    w.scalar(6, "loss", 0.125, wall_time=124.0)
    w.close()

    events = list(tf.compat.v1.train.summary_iterator(w.path))
    # first record is the file_version header
    assert events[0].file_version == "brain.Event:2"
    scalars = [(e.step, v.tag, v.simple_value, e.wall_time)
               for e in events[1:] for v in e.summary.value]
    assert (5, "loss", 0.25, 123.5) in scalars
    assert (5, "accuracy", 0.875, 123.5) in scalars
    assert (6, "loss", 0.125, 124.0) in scalars
    assert len(scalars) == 3


def test_metrics_logger_tb_sink(tmp_path):
    tf = pytest.importorskip("tensorflow")

    ml = MetricsLogger(str(tmp_path / "m.jsonl"),
                       tb_logdir=str(tmp_path / "tb"))
    ml.log({"step": 10, "loss": 1.5, "accuracy": 0.5,
            "eval": {"loss": 2.0}, "note": "not-a-number"})
    ml.log({"no_step_key": 1.0})          # no step -> JSONL only
    ml.close()

    paths = glob.glob(str(tmp_path / "tb" / "events.out.tfevents.*"))
    assert len(paths) == 1
    scalars = [(e.step, v.tag, round(v.simple_value, 6))
               for e in tf.compat.v1.train.summary_iterator(paths[0])
               for v in e.summary.value]
    assert (10, "loss", 1.5) in scalars
    assert (10, "accuracy", 0.5) in scalars
    assert (10, "eval/loss", 2.0) in scalars     # one-level flatten
    assert all(tag != "note" for _, tag, _ in scalars)
    assert len(scalars) == 3
