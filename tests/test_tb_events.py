"""TensorBoard event writer: dependency-free wire format, verified against
TensorFlow's own reader as an oracle (TF is a test-only dependency)."""

import glob

import numpy as np
import pytest

from distributed_tensorflow_example_tpu.data.tfrecord import crc32c
from distributed_tensorflow_example_tpu.utils.metrics import MetricsLogger
from distributed_tensorflow_example_tpu.utils.tb_events import (
    EventFileWriter, _masked_crc)


def test_crc32c_known_vectors():
    # RFC 3720 test vectors (one shared CRC impl with data/tfrecord.py)
    assert crc32c(b"") == 0x0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(bytes(32)) == 0x8A9136AA
    assert _masked_crc(b"123456789") != crc32c(b"123456789")


def test_roundtrip_against_tensorflow_reader(tmp_path):
    tf = pytest.importorskip("tensorflow")

    w = EventFileWriter(str(tmp_path))
    w.scalars(5, {"loss": 0.25, "accuracy": 0.875}, wall_time=123.5)
    w.scalar(6, "loss", 0.125, wall_time=124.0)
    w.close()

    events = list(tf.compat.v1.train.summary_iterator(w.path))
    # first record is the file_version header
    assert events[0].file_version == "brain.Event:2"
    scalars = [(e.step, v.tag, v.simple_value, e.wall_time)
               for e in events[1:] for v in e.summary.value]
    assert (5, "loss", 0.25, 123.5) in scalars
    assert (5, "accuracy", 0.875, 123.5) in scalars
    assert (6, "loss", 0.125, 124.0) in scalars
    assert len(scalars) == 3


def test_metrics_logger_tb_sink(tmp_path):
    tf = pytest.importorskip("tensorflow")

    ml = MetricsLogger(str(tmp_path / "m.jsonl"),
                       tb_logdir=str(tmp_path / "tb"))
    ml.log({"step": 10, "loss": 1.5, "accuracy": 0.5,
            "eval": {"loss": 2.0}, "note": "not-a-number"})
    ml.log({"no_step_key": 1.0})          # no step -> JSONL only
    ml.close()

    paths = glob.glob(str(tmp_path / "tb" / "events.out.tfevents.*"))
    assert len(paths) == 1
    scalars = [(e.step, v.tag, round(v.simple_value, 6))
               for e in tf.compat.v1.train.summary_iterator(paths[0])
               for v in e.summary.value]
    assert (10, "loss", 1.5) in scalars
    assert (10, "accuracy", 0.5) in scalars
    assert (10, "eval/loss", 2.0) in scalars     # one-level flatten
    assert all(tag != "note" for _, tag, _ in scalars)
    assert len(scalars) == 3


def test_histogram_against_tensorflow_reader(tmp_path):
    """tf.summary.histogram parity: TF's summary_iterator must parse our
    HistogramProto with correct moments, and the bucket counts must
    cover every value."""
    tf = pytest.importorskip("tensorflow")
    rs = np.random.RandomState(0)
    vals = np.concatenate([rs.randn(1000) * 2.0, [-7.5, 0.0, 9.25]])
    w = EventFileWriter(str(tmp_path))
    w.histogram(3, "weights/kernel", vals)
    w.close()

    path = glob.glob(str(tmp_path / "events.out.tfevents.*"))[0]
    histos = []
    for ev in tf.compat.v1.train.summary_iterator(path):
        for v in ev.summary.value:
            if v.HasField("histo"):
                histos.append((ev.step, v.tag, v.histo))
    assert len(histos) == 1
    step, tag, h = histos[0]
    assert step == 3 and tag == "weights/kernel"
    assert h.min == pytest.approx(vals.min())
    assert h.max == pytest.approx(vals.max())
    assert h.num == pytest.approx(len(vals))
    assert h.sum == pytest.approx(vals.sum(), rel=1e-9)
    assert h.sum_squares == pytest.approx((vals ** 2).sum(), rel=1e-9)
    assert sum(h.bucket) == pytest.approx(len(vals))
    assert len(h.bucket) == len(h.bucket_limit)
    # limits strictly increasing (TB rendering requirement)
    limits = list(h.bucket_limit)
    assert all(a < b for a, b in zip(limits, limits[1:]))


def test_metrics_logger_histogram_both_sinks(tmp_path):
    jpath = str(tmp_path / "m.jsonl")
    logger = MetricsLogger(jpath, tb_logdir=str(tmp_path / "tb"))
    logger.log_histogram(5, "params/w", np.arange(10.0))
    logger.close()
    import json
    recs = [json.loads(l) for l in open(jpath)]
    h = [r for r in recs if r.get("histogram") == "params/w"]
    assert h and h[0]["count"] == 10 and h[0]["max"] == 9.0
    # TB file got a record too (scalar pollution guarded separately)
    assert glob.glob(str(tmp_path / "tb" / "events.out.tfevents.*"))


def test_param_histogram_hook_end_to_end(tmp_path):
    """--param_histograms_every_steps through the Trainer: JSONL gets
    per-leaf distribution records at the cadence."""
    import json

    from distributed_tensorflow_example_tpu.config import (DataConfig,
                                                           ObservabilityConfig,
                                                           TrainConfig)
    from distributed_tensorflow_example_tpu.data.mnist import (
        synthetic_mnist)
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
    from distributed_tensorflow_example_tpu.train.trainer import Trainer

    data = synthetic_mnist(256, 64)
    jpath = str(tmp_path / "m.jsonl")
    cfg = TrainConfig(model="mlp", train_steps=4,
                      data=DataConfig(batch_size=64),
                      obs=ObservabilityConfig(
                          metrics_path=jpath,
                          param_histograms_every_steps=2))
    tr = Trainer(get_model("mlp", cfg), cfg,
                 {"x": data["train_x"], "y": data["train_y"]},
                 mesh=local_mesh(1, {"data": 1}),
                 process_index=0, num_processes=1)
    tr.train()
    tr.close()
    recs = [json.loads(l) for l in open(jpath)]
    hrecs = [r for r in recs if "histogram" in r]
    steps = sorted({r["step"] for r in hrecs})
    assert steps == [2, 4], steps
    tags = {r["histogram"] for r in hrecs if r["step"] == 2}
    assert any(t.startswith("params/") for t in tags), tags


def test_histogram_nonfinite_values_stay_wellformed(tmp_path):
    """NaN/inf must not overflow the bucket list (malformed proto) —
    the histogram shows the finite distribution, the JSONL surfaces the
    pathology as a nonfinite count."""
    tf = pytest.importorskip("tensorflow")
    vals = np.array([1.0, np.nan, np.inf, -np.inf, 2.0])
    w = EventFileWriter(str(tmp_path))
    w.histogram(1, "w", vals)
    w.close()
    path = glob.glob(str(tmp_path / "events.out.tfevents.*"))[0]
    histos = [v.histo for ev in tf.compat.v1.train.summary_iterator(path)
              for v in ev.summary.value if v.HasField("histo")]
    h = histos[0]
    assert len(h.bucket) == len(h.bucket_limit)
    assert h.num == 2                       # the finite values
    assert sum(h.bucket) == pytest.approx(2)

    import json
    jpath = str(tmp_path / "m.jsonl")
    logger = MetricsLogger(jpath)
    logger.log_histogram(1, "w", vals)
    logger.close()
    rec = [json.loads(l) for l in open(jpath)
           if "histogram" in l][0]
    assert rec["nonfinite"] == 3 and rec["count"] == 5
    assert rec["max"] == 2.0
