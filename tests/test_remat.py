"""Rematerialisation (jax.checkpoint) knob — transformer layers.

Remat is semantics-preserving: loss and gradients must be bit-identical
with it on or off; only the backward-pass memory/recompute trade changes.
Real-chip evidence (TPU v5 lite, BERT-base S=1024 b=8 bf16): temp memory
6607 MiB (none) -> 1096 MiB (full) / 2292 MiB (dots), step 136 -> 187 /
181 ms — recorded in BASELINE.md's long-context envelope.
"""

import jax
import jax.numpy as jnp
import pytest

from distributed_tensorflow_example_tpu.config import TrainConfig
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.models.bert import Bert, BertConfig
from distributed_tensorflow_example_tpu.models.moe import (MoeBert,
                                                           MoeBertConfig)


def _grads(model, params, batch, rng):
    def f(p):
        loss, _ = model.loss(p, {}, batch, rng)
        return loss
    return jax.grad(f)(params)


def _max_leaf_diff(a, b):
    return max(float(jnp.max(jnp.abs(x.astype(jnp.float32)
                                     - y.astype(jnp.float32))))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


@pytest.mark.parametrize("mode", ["full", "dots"])
def test_bert_remat_grad_parity(mode):
    cfg = BertConfig.tiny()
    base = Bert(cfg)
    remat = Bert(cfg, remat=mode)
    params = base.init(jax.random.PRNGKey(1))
    batch = {k: jnp.asarray(v) for k, v in base.dummy_batch(4).items()}
    rng = jax.random.PRNGKey(0)   # dropout active: fold_in must replay
    g0 = _grads(base, params, batch, rng)
    g1 = _grads(remat, params, batch, rng)
    assert _max_leaf_diff(g0, g1) == 0.0


@pytest.mark.parametrize("mode", ["full", "dots"])
def test_moe_bert_remat_grad_parity(mode):
    cfg = MoeBertConfig.tiny()
    base = MoeBert(cfg)
    remat = MoeBert(cfg, remat=mode)
    params = base.init(jax.random.PRNGKey(1))
    batch = {k: jnp.asarray(v) for k, v in base.dummy_batch(4).items()}
    rng = jax.random.PRNGKey(0)
    g0 = _grads(base, params, batch, rng)
    g1 = _grads(remat, params, batch, rng)
    assert _max_leaf_diff(g0, g1) == 0.0


def test_remat_present_in_jaxpr_only_when_enabled():
    cfg = BertConfig.tiny()
    params = Bert(cfg).init(jax.random.PRNGKey(1))
    batch = {k: jnp.asarray(v) for k, v in
             Bert(cfg).dummy_batch(2).items()}

    def jaxpr_of(mode):
        m = Bert(cfg, remat=mode)

        def f(p):
            loss, _ = m.loss(p, {}, batch, jax.random.PRNGKey(0))
            return loss
        return str(jax.make_jaxpr(jax.grad(f))(params))

    assert "remat" in jaxpr_of("full")
    assert "remat" not in jaxpr_of("none")


def test_remat_reaches_models_through_config():
    cfg = TrainConfig(model="bert_tiny", remat="full")
    assert get_model("bert_tiny", cfg).remat == "full"
    assert get_model("moe_bert_tiny", cfg).remat == "full"
    # default stays off
    assert get_model("bert_tiny", TrainConfig(model="bert_tiny")).remat \
        == "none"


def test_invalid_remat_rejected():
    with pytest.raises(ValueError, match="remat"):
        Bert(BertConfig.tiny(), remat="bogus")


def test_remat_composes_with_ring_attention():
    """The long-context recipe composes remat with seq-parallel ring
    attention (docs/DESIGN.md §4): gradients under jax.checkpoint around
    the shard_map ring must match the un-rematerialized ring."""
    from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
    from distributed_tensorflow_example_tpu.parallel.ring_attention import (
        make_ring_attention)

    mesh = local_mesh(8, {"data": 2, "seq": 4})
    cfg = BertConfig.tiny()
    cfg.dropout = 0.0
    ring = make_ring_attention(mesh)
    base = Bert(cfg, attention_fn=ring)
    remat = Bert(cfg, attention_fn=ring, remat="full")
    params = base.init(jax.random.PRNGKey(1))
    batch = {k: jnp.asarray(v) for k, v in base.dummy_batch(4).items()}

    # jit is required: remat (closed_call) can't be evaluated eagerly
    # inside shard_map — and the real training step is always jitted
    def gradfn(model):
        def f(p):
            loss, _ = model.loss(p, {}, batch, None)
            return loss
        return jax.jit(jax.grad(f))

    g0 = gradfn(base)(params)
    g1 = gradfn(remat)(params)
    assert _max_leaf_diff(g0, g1) == 0.0
