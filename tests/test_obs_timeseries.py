"""Metric time-series (obs/timeseries.py): the bounded snapshot ring
and the pure window queries the SLO layer leans on.

Everything runs on fabricated histories with hand-driven clocks — the
sampler's injectable clock and explicit :meth:`~.SnapshotSampler.
sample` calls mean not one test here sleeps.
"""

import pytest

from distributed_tensorflow_example_tpu.obs import timeseries as ts
from distributed_tensorflow_example_tpu.obs.registry import Registry


def _snap(served=0, good=0, tokens=0, lat=()):
    """A real registry snapshot with the SLO-shaped metrics — built
    through the Registry itself so the record layout can never drift
    from what the sampler actually captures."""
    reg = Registry()
    c = reg.counter("serving_slo_served_total")
    g = reg.counter("serving_slo_good_total")
    t = reg.counter("serving_tokens_out_total")
    h = reg.histogram("serving_request_latency_seconds",
                      buckets=(0.1, 1.0, 10.0))
    c.inc(served)
    g.inc(good)
    t.inc(tokens)
    for v in lat:
        h.observe(v)
    return reg.snapshot()


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ------------------------------------------------------------- sampler
def test_sampler_ring_bound_and_injected_clock():
    clock = FakeClock()
    state = {"served": 0}

    def snap():
        return _snap(served=state["served"])

    s = ts.SnapshotSampler(snap, interval_s=1.0, max_samples=3,
                           clock=clock)
    for i in range(5):
        clock.t = float(i)
        state["served"] = i * 10
        s.sample()
    hist = s.history()
    assert len(hist) == 3                      # bounded: oldest dropped
    assert [t for t, _ in hist] == [2.0, 3.0, 4.0]
    assert hist[-1][1]["serving_slo_served_total"]["value"] == 40


def test_sampler_on_sample_hook_runs_and_never_raises_out():
    seen = []

    def hook(sampler):
        seen.append(len(sampler))
        raise RuntimeError("evaluator blew up")

    s = ts.SnapshotSampler(lambda: _snap(), clock=FakeClock(),
                           on_sample=hook)
    s.sample()                                 # must not raise
    s.sample()
    assert seen == [1, 2]


def test_sampler_rejects_bad_config():
    with pytest.raises(ValueError, match="interval_s"):
        ts.SnapshotSampler(dict, interval_s=0)
    with pytest.raises(ValueError, match="max_samples"):
        ts.SnapshotSampler(dict, max_samples=1)


def test_sampler_thread_start_stop_and_immediate_first_sample():
    """start() captures the baseline immediately (no interval wait),
    so a window over a fresh server's ring includes t=0; stop() parks
    the thread even though the interval is an hour."""
    s = ts.SnapshotSampler(lambda: _snap(), interval_s=3600.0)
    s.start()
    try:
        import time
        deadline = time.monotonic() + 5.0
        while len(s) < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(s) >= 1
    finally:
        s.stop()
    assert s._thread is None


# ------------------------------------------------------- window queries
@pytest.fixture
def history():
    return [
        (0.0, _snap(served=0, good=0, tokens=0, lat=[])),
        (10.0, _snap(served=4, good=4, tokens=40, lat=[0.05] * 4)),
        (20.0, _snap(served=10, good=7, tokens=100,
                     lat=[0.05] * 4 + [0.5] * 6)),
    ]


def test_window_selects_by_newest_sample_not_wall_clock(history):
    assert len(ts.window(history, None)) == 3
    assert [t for t, _ in ts.window(history, 10.0)] == [10.0, 20.0]
    assert [t for t, _ in ts.window(history, 5.0)] == [20.0]
    assert ts.window([], 10.0) == []


def test_window_with_explicit_now_excludes_the_future(history):
    """Offline replay at a mid-history instant: samples NEWER than
    ``now`` must be cut too — a burn evaluated at t=10 computed from
    the t=20 sample would page for errors that had not happened yet."""
    assert [t for t, _ in ts.window(history, 60.0, now=10.0)] \
        == [0.0, 10.0]
    assert [t for t, _ in ts.window(history, None, now=10.0)] \
        == [0.0, 10.0]
    assert [t for t, _ in ts.window(history, 5.0, now=12.0)] == [10.0]
    # the replayed instant sees only its own past in the deltas
    assert ts.delta(ts.window(history, 60.0, now=10.0),
                    "serving_slo_served_total") == 4


def test_delta_and_rate(history):
    assert ts.delta(history, "serving_slo_served_total") == 10
    assert ts.rate_per_s(history, "serving_tokens_out_total") == \
        pytest.approx(5.0)
    # sub-window: only the second half's counts
    win = ts.window(history, 10.0)
    assert ts.delta(win, "serving_slo_served_total") == 6
    assert ts.rate_per_s(win, "serving_tokens_out_total") == \
        pytest.approx(6.0)
    # degenerate windows: no rate, no delta
    assert ts.rate_per_s(win[-1:], "serving_tokens_out_total") == 0.0
    assert ts.delta(win[-1:], "serving_slo_served_total") == 0
    assert ts.delta(history, "absent_total") == 0
    with pytest.raises(ValueError, match="histogram"):
        ts.rate_per_s(history, "serving_request_latency_seconds")


def test_histogram_delta_and_window_quantile(history):
    d = ts.delta(history, "serving_request_latency_seconds")
    assert d["count"] == 10
    assert d["buckets"] == [(0.1, 4), (1.0, 6), (10.0, 0)]
    # full window: 4 obs <= 0.1, 6 in (0.1, 1.0] -> p95 inside the
    # second bucket, p30 inside the first
    assert 0.1 < ts.quantile(history, "serving_request_latency_seconds",
                             0.95) <= 1.0
    assert ts.quantile(history, "serving_request_latency_seconds",
                       0.3) <= 0.1
    # the 10s window saw ONLY the six 0.5s observations — the windowed
    # quantile must ignore the fast first wave entirely
    win = ts.window(history, 10.0)
    assert ts.quantile(win, "serving_request_latency_seconds",
                       0.5) > 0.1
    # empty/degenerate -> 0.0 (same convention as an empty histogram)
    assert ts.quantile(win[-1:], "serving_request_latency_seconds",
                       0.5) == 0.0


def test_good_below_interpolates(history):
    name = "serving_request_latency_seconds"
    # at a bucket bound: exact cumulative count
    assert ts.good_below(history, name, 0.1) == 4
    assert ts.good_below(history, name, 1.0) == 10
    # inside the (0.1, 1.0] bucket: linear share of its 6 observations
    mid = ts.good_below(history, name, 0.55)
    assert 4 < mid < 10
    assert mid == pytest.approx(4 + 6 * (0.55 - 0.1) / 0.9)
    assert ts.good_below(history, name, float("inf")) == 10
    assert ts.good_below(history[-1:], name, 1.0) == 0.0


# ------------------------------------------------------------- rollup
def test_rollup_merges_with_clock_offsets():
    """Two replicas sampling the same instants in DIFFERENT clocks
    (replica B's clock runs 100s ahead): with the estimated offsets
    applied, bins align and counters SUM per bin."""
    a = [(0.0, _snap(served=1)), (10.0, _snap(served=3))]
    b = [(100.5, _snap(served=10)), (110.5, _snap(served=30))]
    merged = ts.rollup({"a": a, "b": b},
                       offsets={"b": 100.0}, bin_s=2.0)
    assert len(merged) == 2
    assert [round(t, 1) for t, _ in merged] == [0.5, 10.5]
    assert [s["serving_slo_served_total"]["value"]
            for _, s in merged] == [11, 33]


def test_rollup_skips_bins_missing_a_replica():
    """A bin one replica never covered is dropped — merging the others
    alone would render a fleet-wide counter DIP."""
    a = [(0.0, _snap(served=1)), (10.0, _snap(served=2)),
         (20.0, _snap(served=3))]
    b = [(0.0, _snap(served=5)), (20.0, _snap(served=7))]
    merged = ts.rollup({"a": a, "b": b}, bin_s=1.0)
    assert [int(t) for t, _ in merged] == [0, 20]
    vals = [s["serving_slo_served_total"]["value"] for _, s in merged]
    assert vals == [6, 10]
    assert vals == sorted(vals)                # monotonic by design


def test_rollup_takes_newest_sample_per_bin_and_validates():
    a = [(0.0, _snap(served=1)), (0.9, _snap(served=2))]
    merged = ts.rollup({"a": a}, bin_s=2.0)
    assert len(merged) == 1
    assert merged[0][1]["serving_slo_served_total"]["value"] == 2
    assert ts.rollup({}) == []
    assert ts.rollup({"a": []}) == []
    with pytest.raises(ValueError, match="bin_s"):
        ts.rollup({"a": a}, bin_s=0)


def test_payload_roundtrip(history):
    """JSON round-trip preserves everything the queries read (bucket
    TUPLES come back as lists — both shapes are first-class for every
    window query, so equality is checked through json itself)."""
    import json
    payload = ts.to_payload(history, process="replica0", enabled=True)
    assert payload["process"] == "replica0"
    back = ts.parse_payload(json.loads(json.dumps(payload)))
    assert [t for t, _ in back] == [t for t, _ in history]
    assert json.dumps([s for _, s in back], sort_keys=True) \
        == json.dumps([s for _, s in history], sort_keys=True)
    # and the queries agree across the round-trip
    assert ts.delta(back, "serving_slo_served_total") \
        == ts.delta(history, "serving_slo_served_total")
    assert ts.quantile(back, "serving_request_latency_seconds", 0.95) \
        == ts.quantile(history, "serving_request_latency_seconds",
                       0.95)
