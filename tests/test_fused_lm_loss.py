"""Fused blockwise LM-head cross-entropy: oracle parity, memory
contract, and the lever surface.

The load-bearing claim is the tentpole's: ``lm_loss_impl="fused"``
(ops/losses.py lm_head_xent) must match the full-logits oracle — loss,
token accuracy AND every gradient including the tied-embedding grad —
across weighted/masked/ragged batches and vocab sizes that do NOT
divide the block, while never materializing a [.., V] logits buffer in
forward or backward (HLO-inspected below).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import (MeshShape,
                                                       OptimizerConfig,
                                                       TrainConfig,
                                                       lm_loss_settings)
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.models.gpt import GPT, GPTConfig
from distributed_tensorflow_example_tpu.ops import losses
from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
    SyncReplicas)
from distributed_tensorflow_example_tpu.train.optimizers import (
    make_optimizer)


# ---------------------------------------------------------------------------
# losses-level: fused core vs the explicit-logits reference
# ---------------------------------------------------------------------------

def _ref_nll_argmax(h, table, labels, bias):
    logits = h @ table.T + (0.0 if bias is None else bias)
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return logz - picked, jnp.argmax(logits, axis=-1)


@pytest.mark.parametrize("block", [16, 31, 97, 500])
def test_fused_linear_xent_matches_reference(block):
    """Loss, argmax and ALL grads (h, table, bias) vs the materialized
    oracle, at a prime vocab (97) no block divides evenly."""
    rs = np.random.RandomState(0)
    n, hd, v = 29, 16, 97
    h = jnp.asarray(rs.randn(n, hd).astype(np.float32))
    table = jnp.asarray(rs.randn(v, hd).astype(np.float32))
    bias = jnp.asarray(rs.randn(v).astype(np.float32))
    labels = jnp.asarray(rs.randint(0, v, (n,)).astype(np.int32))
    w = jnp.asarray((rs.rand(n) > 0.3).astype(np.float32))

    def mean(nll):
        return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)

    def ref(h, table, bias):
        return mean(_ref_nll_argmax(h, table, labels, bias)[0])

    def fused(h, table, bias):
        nll, _ = losses.fused_linear_xent(h, table, labels, bias=bias,
                                          vocab_block=block)
        return mean(nll)

    nll, pred = losses.fused_linear_xent(h, table, labels, bias=bias,
                                         vocab_block=block)
    ref_nll, ref_pred = _ref_nll_argmax(h, table, labels, bias)
    np.testing.assert_allclose(np.asarray(nll), np.asarray(ref_nll),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(pred), np.asarray(ref_pred))
    g1 = jax.grad(fused, argnums=(0, 1, 2))(h, table, bias)
    g2 = jax.grad(ref, argnums=(0, 1, 2))(h, table, bias)
    for a, b, name in zip(g1, g2, ("h", "table", "bias")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6, err_msg=name)


def test_fused_argmax_tie_rule_matches_jnp_argmax():
    """Ties resolve to the FIRST index, exactly like jnp.argmax, even
    when the tied columns land in different vocab blocks."""
    h = jnp.asarray([[1.0]])
    table = jnp.asarray([[0.0], [2.0], [2.0], [1.0]])   # cols 1,2 tie
    labels = jnp.asarray([0], jnp.int32)
    for block in (1, 2, 3, 4):
        _, pred = losses.fused_linear_xent(h, table, labels,
                                           vocab_block=block)
        assert int(pred[0]) == 1, (block, int(pred[0]))


def test_lm_head_xent_impl_validation_is_loud():
    h = jnp.zeros((2, 3, 4))
    t = jnp.zeros((7, 4))
    lab = jnp.zeros((2, 3), jnp.int32)
    w = jnp.ones((2, 3))
    with pytest.raises(ValueError, match="lm_loss_impl"):
        losses.lm_head_xent(h, t, lab, w, impl="bogus")
    with pytest.raises(ValueError, match="vocab_block"):
        losses.lm_head_xent(h, t, lab, w, impl="full", vocab_block=4)
    with pytest.raises(ValueError, match="seq_chunk"):
        losses.lm_head_xent(h, t, lab, w, impl="fused", seq_chunk=2)
    with pytest.raises(ValueError, match="chunked"):
        losses.lm_head_xent(h, t, lab, w, impl="chunked")


def test_weighted_token_mean_skipped_accuracy_sentinel():
    nll = jnp.asarray([1.0, 3.0])
    w = jnp.asarray([1.0, 1.0])
    loss, acc = losses.weighted_token_mean(nll, None, w)
    assert float(loss) == pytest.approx(2.0)
    assert float(acc) == -1.0


# ---------------------------------------------------------------------------
# GPT: fused vs full oracle across batch regimes
# ---------------------------------------------------------------------------

def _gpt_pair(vocab_block):
    cfg = GPTConfig.tiny()
    cfg.dropout = 0.0
    full = GPT(cfg)
    cfg2 = GPTConfig.tiny()
    cfg2.dropout = 0.0
    cfg2.loss_impl = "fused"
    cfg2.loss_vocab_block = vocab_block
    return full, GPT(cfg2)


def _batches():
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 1000, (4, 32), dtype=np.int32)
    full_mask = np.ones_like(ids)
    ragged = np.ones_like(ids)
    for i, n in enumerate((32, 20, 7, 1)):
        ragged[i, n:] = 0
    holes = (rs.rand(4, 32) > 0.25).astype(np.int32)
    return [("unweighted", full_mask), ("ragged", ragged),
            ("masked", holes)]


@pytest.mark.parametrize("vocab_block", [128, 300, 1000, 4096])
def test_gpt_fused_matches_full_oracle(vocab_block):
    """Loss, token_accuracy and ALL param grads — including the tied
    embedding wte/table — match the full-logits oracle across
    unweighted/ragged/masked batches; 1000-vocab blocks of 128/300
    exercise the vocab-not-divisible padding, 4096 the block > V case."""
    full, fused = _gpt_pair(vocab_block)
    params = full.init(jax.random.key(0))
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 1000, (4, 32), dtype=np.int32)
    for name, mask in _batches():
        batch = {"input_ids": jnp.asarray(ids),
                 "attention_mask": jnp.asarray(mask)}
        (l1, (a1, _)), g1 = jax.jit(jax.value_and_grad(
            lambda p: full.loss(p, {}, batch, None),
            has_aux=True))(params)
        (l2, (a2, _)), g2 = jax.jit(jax.value_and_grad(
            lambda p: fused.loss(p, {}, batch, None),
            has_aux=True))(params)
        np.testing.assert_allclose(float(l2), float(l1), rtol=1e-6,
                                   err_msg=name)
        np.testing.assert_allclose(float(a2["token_accuracy"]),
                                   float(a1["token_accuracy"]),
                                   rtol=1e-6, err_msg=name)
        np.testing.assert_allclose(
            np.asarray(g2["wte"]["table"]), np.asarray(g1["wte"]["table"]),
            rtol=2e-5, atol=1e-6, err_msg=f"{name}: tied-embedding grad")
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6,
                err_msg=name), g2, g1)


def test_gpt_fused_eval_metrics_match_full_incl_valid_mask():
    full, fused = _gpt_pair(0)
    params = full.init(jax.random.key(1))
    b = full.dummy_batch(4)
    b["__valid__"] = np.asarray([1, 1, 0, 1], np.float32)
    ef = full.eval_metrics(params, {}, b)
    eu = fused.eval_metrics(params, {}, b)
    for k in ("loss", "perplexity", "token_accuracy"):
        np.testing.assert_allclose(float(eu[k]), float(ef[k]),
                                   rtol=1e-6, err_msg=k)


def test_gpt_fused_matches_chunked():
    """The three impls form one equivalence class: fused == chunked
    (which the seed already proved == full)."""
    cfg = GPTConfig.tiny()
    cfg.dropout = 0.0
    cfg.loss_chunk = 16           # legacy spelling -> impl "chunked"
    chunked = GPT(cfg)
    assert chunked.cfg.loss_impl == "chunked"
    _, fused = _gpt_pair(256)
    params = chunked.init(jax.random.key(2))
    batch = chunked.dummy_batch(4)
    l1, (a1, _) = chunked.loss(params, {}, batch, None)
    l2, (a2, _) = fused.loss(params, {}, batch, None)
    np.testing.assert_allclose(float(l2), float(l1), rtol=1e-6)
    np.testing.assert_allclose(float(a2["token_accuracy"]),
                               float(a1["token_accuracy"]), rtol=1e-6)


def test_gpt_fused_trains_under_tp_mesh(cpu8):
    """{data:2, model:2, fsdp:2}: the fused vocab scan composes with the
    vocab-sharded tied head — training still converges."""
    mesh = local_mesh(8, {"data": 2, "fsdp": 2, "model": 2})
    m = get_model("gpt_tiny", TrainConfig(model="gpt_tiny",
                                          lm_loss_impl="fused",
                                          lm_loss_vocab_block=256))
    shape = MeshShape(data=2, fsdp=2, model=2)
    tx = make_optimizer(OptimizerConfig(name="adamw", learning_rate=1e-3))
    sync = SyncReplicas(m.loss, tx, mesh, rules=m.sharding_rules(shape))
    state = sync.init(m.init)
    batch = sync.shard_batch(m.dummy_batch(16))
    vals = []
    for _ in range(6):
        state, metrics = sync.step(state, batch)
        vals.append(float(metrics["loss"]))
    assert vals[-1] < vals[0], vals


# ---------------------------------------------------------------------------
# the memory contract: no [.., V] logits buffer on the fused path
# ---------------------------------------------------------------------------

def test_fused_hlo_has_no_full_vocab_logits_buffer():
    """HLO inspection (the CPU-runnable stand-in for the on-chip peak
    check): the fused train-loss program contains NO buffer shaped like
    the full [B, S, V] (or flattened [B*S, V]) logits, while the full
    oracle's program does — so the string probe is proven able to see
    the tensor it asserts away."""
    full, fused = _gpt_pair(128)     # V=1000, b2 s16 -> N=32
    params = full.init(jax.random.key(0))
    rs = np.random.RandomState(3)
    batch = {"input_ids": jnp.asarray(
        rs.randint(0, 1000, (2, 16), dtype=np.int32))}

    def lowered_text(model):
        def train_loss(p):
            return model.loss(p, {}, batch, None)[0]
        return jax.jit(jax.grad(train_loss)).lower(params).as_text()

    probes = ("2,16,1000", "32x1000", "32,1000", "2x16x1000")

    def mentions_logits(txt):
        return any(p in txt for p in probes)

    assert mentions_logits(lowered_text(full)), \
        "probe failed to see the oracle's logits buffer — fix the probe"
    assert not mentions_logits(lowered_text(fused)), \
        "fused path materialized a full-vocab logits buffer"


# ---------------------------------------------------------------------------
# BERT family through the shared core
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("model_name", ["bert_tiny", "moe_bert_tiny"])
def test_bert_fused_matches_gather_path(model_name):
    """BERT's masked-LM loss through the fused core vs its existing
    gather-based full path: loss, accuracy, and grads (tied word
    embedding + mlm bias) — it touches only max_predictions positions,
    so the assertion is parity, not a win."""
    full = get_model(model_name, TrainConfig(model=model_name))
    fused = get_model(model_name, TrainConfig(model=model_name,
                                              lm_loss_impl="fused",
                                              lm_loss_vocab_block=300))
    params = full.init(jax.random.key(0))
    batch = full.dummy_batch(4)
    batch["masked_weights"][:, -3:] = 0.0        # weighted positions
    rng = jax.random.key(1)
    (l1, (a1, _)), g1 = jax.jit(jax.value_and_grad(
        lambda p: full.loss(p, {}, batch, rng), has_aux=True))(params)
    (l2, (a2, _)), g2 = jax.jit(jax.value_and_grad(
        lambda p: fused.loss(p, {}, batch, rng), has_aux=True))(params)
    np.testing.assert_allclose(float(l2), float(l1), rtol=1e-6)
    np.testing.assert_allclose(float(a2["mlm_accuracy"]),
                               float(a1["mlm_accuracy"]), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(g2["embed"]["word"]["table"]),
        np.asarray(g1["embed"]["word"]["table"]),
        rtol=2e-5, atol=1e-6, err_msg="tied word-embedding grad")
    np.testing.assert_allclose(
        np.asarray(g2["mlm"]["bias"]), np.asarray(g1["mlm"]["bias"]),
        rtol=2e-5, atol=1e-6, err_msg="mlm bias grad")
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6), g2, g1)
    # eval rides it too, incl. the padded static-shape tail
    eb = dict(batch)
    eb["__valid__"] = np.asarray([1, 0, 1, 1], np.float32)
    ef = full.eval_metrics(params, {}, eb)
    eu = fused.eval_metrics(params, {}, eb)
    for k in ("loss", "mlm_accuracy"):
        np.testing.assert_allclose(float(eu[k]), float(ef[k]),
                                   rtol=1e-6, err_msg=k)


def test_bert_rejects_chunked_impl():
    from distributed_tensorflow_example_tpu.models.bert import (Bert,
                                                                BertConfig)
    cfg = BertConfig.tiny()
    cfg.lm_loss_impl = "chunked"
    with pytest.raises(ValueError, match="causal"):
        Bert(cfg)


# ---------------------------------------------------------------------------
# the token_accuracy_every_n lever
# ---------------------------------------------------------------------------

def test_token_accuracy_every_n_cadence(cpu8):
    """n=2: the argmax runs on every 2nd step (others publish the -1.0
    skipped sentinel), the loss stream is bit-identical to n=1, and the
    step counter rides TrainState.extras."""
    mesh = local_mesh(8, {"data": 8})
    tx = make_optimizer(OptimizerConfig(name="adamw", learning_rate=1e-3))

    def run(every):
        m = get_model("gpt_tiny", TrainConfig(
            model="gpt_tiny", token_accuracy_every_n=every))
        sync = SyncReplicas(m.loss, tx, mesh)
        state = sync.init(m.init)
        batch = sync.shard_batch(m.dummy_batch(16))
        out = []
        for _ in range(4):
            state, metrics = sync.step(state, batch)
            out.append((float(metrics["loss"]),
                        float(metrics["token_accuracy"])))
        return out

    base, every2 = run(1), run(2)
    for (l1, a1), (l2, a2), i in zip(base, every2, range(4)):
        assert l2 == pytest.approx(l1, rel=1e-6), i   # loss unaffected
        if i % 2 == 0:
            assert a2 == pytest.approx(a1, rel=1e-6), i
        else:
            assert a2 == -1.0, (i, a2)


def test_token_accuracy_every_n_direct_call_without_counter():
    """Direct loss() calls that never initialized the extras counter
    (every test and notebook does this) keep working — accuracy is
    simply always computed."""
    m = get_model("gpt_tiny", TrainConfig(model="gpt_tiny",
                                          token_accuracy_every_n=3))
    params, extras = m.init(jax.random.key(0))
    assert "lm_step" in extras
    l, (aux, new_extras) = m.loss(params, {}, m.dummy_batch(4),
                                  jax.random.key(1))
    assert float(aux["token_accuracy"]) >= 0.0
    assert new_extras == {}


# ---------------------------------------------------------------------------
# lever-surface validation: config, model, CLI — all loud
# ---------------------------------------------------------------------------

def test_config_lm_loss_settings_validation():
    ok = lm_loss_settings(TrainConfig(lm_loss_impl="fused",
                                      lm_loss_vocab_block=512))
    assert ok == {"impl": "fused", "chunk": 0, "vocab_block": 512,
                  "accuracy_every_n": 1}
    legacy = lm_loss_settings(TrainConfig(lm_loss_chunk=64))
    assert legacy["impl"] == "chunked" and legacy["chunk"] == 64
    for bad in (TrainConfig(lm_loss_impl="bogus"),
                TrainConfig(lm_loss_impl="chunked"),
                TrainConfig(lm_loss_impl="fused", lm_loss_chunk=64),
                TrainConfig(lm_loss_impl="full", lm_loss_chunk=64),
                TrainConfig(lm_loss_vocab_block=128),
                TrainConfig(lm_loss_vocab_block=-1),
                TrainConfig(lm_loss_chunk=-1),
                TrainConfig(token_accuracy_every_n=0),
                # fused computes accuracy for free: the cadence knob
                # would be silently ignored — rejected instead
                TrainConfig(lm_loss_impl="fused",
                            token_accuracy_every_n=4)):
        with pytest.raises(ValueError):
            lm_loss_settings(bad)
    # microbatch accumulation would average real accuracies with the
    # -1.0 skipped sentinel (the loss runs per microbatch) — rejected
    bad = TrainConfig(token_accuracy_every_n=2)
    bad.sync.accum_steps = 2
    with pytest.raises(ValueError, match="accum_steps"):
        lm_loss_settings(bad)


def test_gpt_model_level_validation_is_loud():
    for mutate, match in (
            (lambda c: setattr(c, "loss_impl", "bogus"), "lm_loss_impl"),
            (lambda c: setattr(c, "loss_impl", "chunked"),
             "lm_loss_chunk"),
            (lambda c: setattr(c, "loss_vocab_block", -2),
             "lm_loss_vocab_block"),
            (lambda c: (setattr(c, "loss_impl", "fused"),
                        setattr(c, "loss_chunk", 8)), "conflicts"),
            (lambda c: setattr(c, "loss_vocab_block", 64),
             "fused")):
        cfg = GPTConfig.tiny()
        mutate(cfg)
        with pytest.raises(ValueError, match=match):
            GPT(cfg)
    with pytest.raises(ValueError, match="token_accuracy_every_n"):
        GPT(GPTConfig.tiny(), accuracy_every_n=0)
    # fused + cadence knob is rejected at MODEL level too (direct
    # construction bypasses config.lm_loss_settings)
    cfg = GPTConfig.tiny()
    cfg.loss_impl = "fused"
    with pytest.raises(ValueError, match="no extra cost"):
        GPT(cfg, accuracy_every_n=2)


def test_cli_lever_gating_is_loud():
    from distributed_tensorflow_example_tpu.cli.train import main
    with pytest.raises(SystemExit, match="LM-head"):
        main(["--model", "mlp", "--train_steps", "1",
              "--lm_loss_impl", "fused"])
    with pytest.raises(SystemExit, match="LM-head"):
        main(["--model", "resnet20", "--train_steps", "1",
              "--lm_loss_vocab_block", "512"])
    with pytest.raises(SystemExit, match="causal-LM"):
        main(["--model", "bert_tiny", "--train_steps", "1",
              "--token_accuracy_every_n", "4"])
    with pytest.raises(SystemExit, match="fused"):
        main(["--model", "gpt_tiny", "--train_steps", "1",
              "--lm_loss_vocab_block", "512"])
    with pytest.raises(SystemExit, match="conflicts"):
        main(["--model", "gpt_tiny", "--train_steps", "1",
              "--lm_loss_impl", "fused", "--lm_loss_chunk", "64"])
    with pytest.raises(SystemExit):      # argparse rejects the choice
        main(["--model", "gpt_tiny", "--train_steps", "1",
              "--lm_loss_impl", "bogus"])


def test_cli_gpt_trains_fused_without_lm_loss_chunk(cpu8):
    """The acceptance path: a fused CLI run trains end-to-end with NO
    --lm_loss_chunk anywhere."""
    from distributed_tensorflow_example_tpu.cli.train import main
    rc = main(["--model", "gpt_tiny", "--train_steps", "2",
               "--batch_size", "16", "--mesh", "data=8",
               "--optimizer", "adamw", "--learning_rate", "1e-3",
               "--lm_loss_impl", "fused", "--lm_loss_vocab_block", "256"])
    assert rc == 0
    # the cadence knob on the fused path would be silently inert
    # (fused's accuracy is free) — rejected loudly instead
    with pytest.raises(SystemExit, match="no extra cost"):
        main(["--model", "gpt_tiny", "--train_steps", "1",
              "--lm_loss_impl", "fused",
              "--token_accuracy_every_n", "2"])
