"""tools/servetop.py: the SLO & goodput renderer.

compute_summary is pure (fabricated payloads, no network, no clocks),
and the offline ``--file`` mode is driven through main() — the same
path an operator uses on a dumped history or an slo_burn bundle's
tail. The live-poll path is exercised end-to-end by the serving_load
``slo_report`` smoke leg, which reconciles compute_summary against
the harness ledger exactly.
"""

import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from distributed_tensorflow_example_tpu.obs.registry import (  # noqa: E402
    Registry)
from tools import servetop  # noqa: E402


def _snap(interactive=(0, 0), best_effort=(0, 0), tokens=0,
          goodput=0, shed_be=0, queue=0, pressure=0):
    reg = Registry()
    for cls, (served, good) in (("interactive", interactive),
                                ("batch", (0, 0)),
                                ("best_effort", best_effort)):
        reg.counter(f"serving_slo_served_{cls}_total").inc(served)
        reg.counter(f"serving_slo_good_{cls}_total").inc(good)
        reg.histogram(f"serving_latency_{cls}_seconds",
                      buckets=(0.1, 1.0))
    reg.counter("serving_slo_served_total").inc(
        interactive[0] + best_effort[0])
    reg.counter("serving_slo_good_total").inc(
        interactive[1] + best_effort[1])
    reg.counter("serving_tokens_out_total").inc(tokens)
    reg.counter("serving_goodput_tokens_total").inc(goodput)
    reg.counter("serving_shed_total").inc(shed_be)
    reg.counter("serving_shed_interactive_total")
    reg.counter("serving_shed_batch_total")
    reg.counter("serving_shed_best_effort_total").inc(shed_be)
    reg.gauge("serving_queue_depth").set(queue)
    reg.gauge("serving_queue_age_seconds").set(0.0)
    reg.gauge("serving_pressure_level").set(pressure)
    return reg.snapshot()


@pytest.fixture
def payload():
    return {
        "enabled": True, "process": "serving", "clock": 20.0,
        "interval_s": 10.0,
        "samples": [
            [0.0, _snap()],
            [10.0, _snap(interactive=(4, 4), tokens=40, goodput=40)],
            [20.0, _snap(interactive=(8, 7), best_effort=(4, 2),
                         tokens=100, goodput=80, shed_be=2,
                         queue=3, pressure=1)],
        ],
        "slo": {"results": [
            {"class": "best_effort", "kind": "hit_rate",
             "target": 0.9, "goal": 0.9, "attainment": 0.5,
             "burn_fast": 5.0, "burn_slow": 5.0, "breach": True}]},
    }


def test_compute_summary_is_exact(payload):
    s = servetop.compute_summary(payload)
    assert s["enabled"] and s["samples"] == 3
    assert s["window_s"] == 20.0
    assert s["throughput_tps"] == pytest.approx(5.0)
    assert s["goodput_tps"] == pytest.approx(4.0)
    assert s["served"] == 12 and s["good"] == 9
    assert s["goodput_tokens"] == 80 and s["tokens"] == 100
    assert s["queue_depth"] == 3
    assert s["pressure"] == "shed_best_effort"
    ci = s["classes"]["interactive"]
    assert (ci["served"], ci["good"], ci["shed"]) == (8, 7, 0)
    assert ci["attainment"] == pytest.approx(7 / 8)
    cb = s["classes"]["best_effort"]
    assert (cb["served"], cb["good"], cb["shed"]) == (4, 2, 2)
    assert s["classes"]["batch"]["attainment"] is None
    assert s["slo"][0]["breach"] is True


def test_compute_summary_windowed(payload):
    s = servetop.compute_summary(payload, window_s=10.0)
    # only the last 10s: the second wave's deltas
    assert s["served"] == 8 and s["tokens"] == 60
    assert s["classes"]["interactive"]["served"] == 4


def test_compute_summary_fleet_breakdown(payload):
    payload["process"] = "router"
    payload["replicas"] = {
        "replica0": {"enabled": True, "clock_offset_s": 0.000123,
                     "samples": payload["samples"]},
        "replica1": {"error": "ConnectionRefusedError: dead"},
    }
    s = servetop.compute_summary(payload)
    r0 = s["replicas"]["replica0"]
    assert r0["served"] == 12
    assert r0["attainment"] == pytest.approx(9 / 12)
    assert r0["clock_offset_s"] == 0.000123
    assert "error" in s["replicas"]["replica1"]


def test_render_frame_mentions_the_story(payload):
    payload["replicas"] = {
        "replica0": {"enabled": True, "clock_offset_s": 0.0,
                     "samples": payload["samples"]}}
    text = servetop.render(servetop.compute_summary(payload))
    for needle in ("goodput", "interactive", "best_effort",
                   "BREACH", "replica0", "shed_best_effort"):
        assert needle in text, needle
    # a sampler-off payload renders the how-to-arm hint, not a crash
    off = servetop.render(servetop.compute_summary(
        {"enabled": False, "process": "serving", "samples": []}))
    assert "--history_interval_s" in off


def test_main_offline_file_mode(tmp_path, capsys, payload):
    p = tmp_path / "hist.json"
    p.write_text(json.dumps(payload))
    assert servetop.main(["--file", str(p)]) == 0
    out = capsys.readouterr().out
    assert "servetop — serving" in out
    assert servetop.main(["--file", str(p), "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["served"] == 12
    # windowed offline render
    assert servetop.main(["--file", str(p), "--json",
                          "--window", "10"]) == 0
    assert json.loads(capsys.readouterr().out)["served"] == 8
