"""MoE / expert-parallelism tests (VERDICT r2 missing #1).

Covers the dense dispatch/combine path against a per-token brute-force
oracle, dense == explicit all_to_all EP on the virtual mesh (outputs AND
the now-global aux loss), capacity-overflow drop semantics, gradients
(finite everywhere, nonzero at the router), and end-to-end MoeBert
training under SyncReplicas with expert-sharded rules.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_example_tpu.config import (MeshShape,
                                                       OptimizerConfig,
                                                       TrainConfig)
from distributed_tensorflow_example_tpu.data.bert_data import get_bert_data
from distributed_tensorflow_example_tpu.models import get_model, list_models
from distributed_tensorflow_example_tpu.models.moe import (MoeBert,
                                                           MoeBertConfig)
from distributed_tensorflow_example_tpu.ops import moe
from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
    SyncReplicas)
from distributed_tensorflow_example_tpu.train.optimizers import make_optimizer


def _params(n_experts=4, hidden=16, inter=32, seed=0):
    return moe.moe_ffn_init(jax.random.key(seed), n_experts, hidden, inter)


# ---------------------------------------------------------------------------
# registry (ADVICE r2 finding 1: the module was never imported)
# ---------------------------------------------------------------------------

def test_moe_models_registered():
    assert "moe_bert" in list_models()
    assert "moe_bert_tiny" in list_models()
    m = get_model("moe_bert_tiny", TrainConfig(model="moe_bert_tiny"))
    assert isinstance(m, MoeBert)


# ---------------------------------------------------------------------------
# dense path == per-token brute-force routing oracle
# ---------------------------------------------------------------------------

def _brute_force_top1(params, x2):
    """out[t] = gate_t * FFN_{argmax expert}(x_t) — no dispatch tensors."""
    logits = x2 @ params["router"]["kernel"]
    probs = jax.nn.softmax(logits, axis=-1)
    e = jnp.argmax(probs, axis=-1)                          # [T]
    gate = jnp.take_along_axis(probs, e[:, None], axis=1)[:, 0]
    h = jnp.einsum("td,tdh->th", x2, params["w_in"][e]) + params["b_in"][e]
    h = jax.nn.gelu(h)
    out = (jnp.einsum("th,thd->td", h, params["w_out"][e])
           + params["b_out"][e])
    return gate[:, None] * out


def test_moe_ffn_matches_bruteforce_top1():
    params = _params()
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, 8, 16).astype(np.float32))
    got, _ = moe.moe_ffn(params, x, n_experts=4, top_k=1,
                         capacity_factor=8.0)
    want = _brute_force_top1(params, x.reshape(16, 16)).reshape(2, 8, 16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# dense == explicit all_to_all expert parallelism (outputs and aux)
# ---------------------------------------------------------------------------

def test_moe_dense_equals_shard_map(cpu8):
    mesh = local_mesh(8, {"data": 2, "expert": 4})
    params = _params(n_experts=4, hidden=16, inter=32)
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(4, 16, 16).astype(np.float32))
    # generous capacity: per-shard capacity must fit every token so the
    # two paths drop nothing (see moe_ffn_shard_map docstring)
    dense, aux_d = moe.moe_ffn(params, x, n_experts=4, capacity_factor=8.0)
    ep, aux_e = moe.moe_ffn_shard_map(params, x, mesh, n_experts=4,
                                      capacity_factor=8.0,
                                      batch_axes=("data",))
    np.testing.assert_allclose(np.asarray(ep), np.asarray(dense),
                               rtol=1e-5, atol=1e-6)
    # aux statistics are pmean'd to GLOBAL batch values before the formula
    # (ADVICE r2 finding 4), so the two paths agree — for the loss terms
    # AND the visibility stats (nothing dropped; per-rank capacity divides
    # evenly, so slot-utilization means match too)
    for k in ("lb_loss", "z_loss", "dropped_fraction"):
        np.testing.assert_allclose(float(aux_e[k]), float(aux_d[k]),
                                   rtol=1e-5, atol=1e-7, err_msg=k)
    np.testing.assert_allclose(np.asarray(aux_e["expert_load"]),
                               np.asarray(aux_d["expert_load"]),
                               rtol=1e-5, atol=1e-7)


def test_moe_shard_map_top2(cpu8):
    mesh = local_mesh(8, {"data": 2, "expert": 4})
    params = _params(n_experts=4, hidden=16, inter=32, seed=3)
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(4, 16, 16).astype(np.float32))
    dense, aux_d = moe.moe_ffn(params, x, n_experts=4, top_k=2,
                               capacity_factor=8.0)
    ep, aux_e = moe.moe_ffn_shard_map(params, x, mesh, n_experts=4,
                                      top_k=2, capacity_factor=8.0,
                                      batch_axes=("data",))
    np.testing.assert_allclose(np.asarray(ep), np.asarray(dense),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux_e["lb_loss"]),
                               float(aux_d["lb_loss"]), rtol=1e-5)


# ---------------------------------------------------------------------------
# capacity overflow: dropped tokens contribute zero (residual handles them)
# ---------------------------------------------------------------------------

def test_moe_capacity_overflow_drops_tokens():
    params = _params(n_experts=4, hidden=16, inter=32)
    # zero router -> every token argmaxes to expert 0 with gate 0.25
    params["router"]["kernel"] = jnp.zeros_like(params["router"]["kernel"])
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(1, 8, 16).astype(np.float32))
    # T=8, E=4, factor=1.0 -> capacity 2: tokens 0,1 keep, 2..7 dropped
    out, _ = moe.moe_ffn(params, x, n_experts=4, capacity_factor=1.0)
    out = np.asarray(out)[0]
    assert np.abs(out[:2]).max() > 0
    np.testing.assert_array_equal(out[2:], np.zeros_like(out[2:]))
    # generous capacity keeps everyone
    full, _ = moe.moe_ffn(params, x, n_experts=4, capacity_factor=8.0)
    assert np.abs(np.asarray(full)[0]).min(axis=-1).max() > 0


# ---------------------------------------------------------------------------
# gradients
# ---------------------------------------------------------------------------

def test_moe_gradients_finite_router_nonzero():
    params = _params()
    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(2, 8, 16).astype(np.float32))

    def loss_fn(p):
        out, aux = moe.moe_ffn(p, x, n_experts=4, capacity_factor=2.0)
        return jnp.sum(jnp.square(out)) + aux["lb_loss"] + aux["z_loss"]

    grads = jax.jit(jax.grad(loss_fn))(params)
    for leaf in jax.tree_util.tree_leaves(grads):
        assert np.all(np.isfinite(np.asarray(leaf)))
    # the router must receive gradient through the gate AND the aux loss
    assert np.abs(np.asarray(grads["router"]["kernel"])).max() > 0


def test_moe_shard_map_gradients_match_dense(cpu8):
    mesh = local_mesh(8, {"data": 2, "expert": 4})
    params = _params(n_experts=4, hidden=16, inter=32, seed=5)
    rs = np.random.RandomState(5)
    x = jnp.asarray(rs.randn(4, 16, 16).astype(np.float32))

    def loss_dense(p):
        out, aux = moe.moe_ffn(p, x, n_experts=4, capacity_factor=8.0)
        return jnp.sum(jnp.square(out)) + aux["lb_loss"]

    def loss_ep(p):
        out, aux = moe.moe_ffn_shard_map(p, x, mesh, n_experts=4,
                                         capacity_factor=8.0,
                                         batch_axes=("data",))
        return jnp.sum(jnp.square(out)) + aux["lb_loss"]

    g_d = jax.jit(jax.grad(loss_dense))(params)
    g_e = jax.jit(jax.grad(loss_ep))(params)
    for kd, ke in zip(jax.tree_util.tree_leaves(g_d),
                      jax.tree_util.tree_leaves(g_e)):
        np.testing.assert_allclose(np.asarray(ke), np.asarray(kd),
                                   rtol=2e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# MoeBert end-to-end
# ---------------------------------------------------------------------------

def _tiny_moe():
    cfg = MoeBertConfig.tiny()
    cfg.dropout = 0.0
    return MoeBert(cfg)


def test_moe_bert_tiny_loss_and_eval():
    m = _tiny_moe()
    params = m.init(jax.random.key(0))
    batch = m.dummy_batch(2)
    loss, (aux, _) = m.loss(params, {}, batch, jax.random.key(1))
    assert np.isfinite(float(loss))
    assert float(aux["aux_loss"]) > 0          # routers actually routed
    # eval path goes through the inherited apply(): no _last_aux channel
    metrics = m.eval_metrics(params, {}, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_moe_bert_no_tracer_leak():
    """loss() must not stash tracers on self (VERDICT r2 weak #5)."""
    m = _tiny_moe()
    params = m.init(jax.random.key(0))
    batch = m.dummy_batch(2)
    with jax.check_tracer_leaks():
        loss, _ = jax.jit(
            lambda p: m.loss(p, {}, batch, jax.random.key(1)))(params)
    assert not any(isinstance(v, jax.core.Tracer) for v in vars(m).values())
    assert np.isfinite(float(loss))


def test_moe_bert_learns_expert_sharded(cpu8):
    """MoeBert trains (loss decreases) under SyncReplicas on a
    {data:2, expert:4} mesh with expert-sharded weights."""
    mesh = local_mesh(8, {"data": 2, "expert": 4})
    m = _tiny_moe()
    rules = m.sharding_rules(MeshShape(data=2, expert=4))
    assert any("moe" in pat for pat, _ in rules.rules)
    tx = make_optimizer(OptimizerConfig(name="adam", learning_rate=1e-3))
    sync = SyncReplicas(m.loss, tx, mesh, rules=rules)
    state = sync.init(m.init, seed=0)
    tr, _ = get_bert_data(None, vocab_size=m.cfg.vocab_size, seq_len=64,
                          num_train=64, num_test=8)
    losses = []
    for i in range(15):
        lo = (i % 2) * 32
        b = {k: v[lo:lo + 32] for k, v in tr.items()}
        state, metr = sync.step(state, sync.shard_batch(b))
        losses.append(float(metr["loss"]))
    assert losses[-1] < losses[0]


def _brute_force_topk(params, x2, k):
    """out[t] = sum over the k best experts of gate * FFN_e(x_t), with
    the repeated-masked-argmax expert order and RAW (unrenormalized)
    chosen probabilities — the _route contract."""
    logits = x2 @ params["router"]["kernel"]
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    out = np.zeros_like(np.asarray(x2))
    for t in range(x2.shape[0]):
        remaining = probs[t].copy()
        for _ in range(k):
            e = int(np.argmax(remaining))
            gate = probs[t][e]
            h = np.asarray(x2[t]) @ np.asarray(params["w_in"][e]) \
                + np.asarray(params["b_in"][e])
            h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
            out[t] += gate * (h @ np.asarray(params["w_out"][e])
                              + np.asarray(params["b_out"][e]))
            remaining[e] = 0.0
    return out


def test_moe_ffn_matches_bruteforce_top2():
    """Top-2 gating (the classic MoE recipe) against the per-token
    oracle at generous capacity."""
    params = _params()
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(2, 8, 16).astype(np.float32))
    got, _ = moe.moe_ffn(params, x, n_experts=4, top_k=2,
                         capacity_factor=8.0)
    want = _brute_force_topk(params, x.reshape(16, 16), 2).reshape(2, 8, 16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_moe_cli_knobs_reach_the_model():
    cfg = TrainConfig(model="moe_bert_tiny", moe_experts=2, moe_top_k=2,
                      moe_capacity_factor=3.0)
    m = get_model("moe_bert_tiny", cfg)
    assert m.cfg.n_experts == 2
    assert m.cfg.top_k == 2
    assert m.cfg.capacity_factor == 3.0
    # top_k out of range errors — including via --moe_experts alone
    with pytest.raises(ValueError, match="moe_top_k"):
        get_model("moe_bert_tiny",
                  TrainConfig(model="moe_bert_tiny", moe_top_k=9))
    with pytest.raises(ValueError, match="moe_experts"):
        get_model("moe_bert_tiny",
                  TrainConfig(model="moe_bert_tiny", moe_experts=0))
    with pytest.raises(ValueError, match="capacity_factor"):
        get_model("moe_bert_tiny",
                  TrainConfig(model="moe_bert_tiny",
                              moe_capacity_factor=0.0))


def test_moe_cli_guard_rejects_non_moe_model():
    from distributed_tensorflow_example_tpu.cli.train import main
    with pytest.raises(SystemExit, match="moe"):
        main(["--model", "mlp", "--train_steps", "1", "--moe_top_k", "2"])


def test_moe_bert_tiny_trains_top2(cpu8):
    """top-2 routing trains end to end on the {data, expert} mesh."""
    cfg = TrainConfig(model="moe_bert_tiny", moe_top_k=2,
                      moe_capacity_factor=4.0)
    m = get_model("moe_bert_tiny", cfg)
    mesh = local_mesh(8, {"data": 2, "expert": 4})
    tx = make_optimizer(OptimizerConfig(name="adamw", learning_rate=1e-3))
    sync = SyncReplicas(m.loss, tx, mesh,
                        rules=m.sharding_rules(MeshShape(data=2,
                                                         expert=4)))
    state = sync.init(m.init)
    batch = sync.shard_batch(m.dummy_batch(16))
    losses = []
    for _ in range(6):
        state, metrics = sync.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


# ---------------------------------------------------------------------------
# training-quality mechanisms (VERDICT r3 weak #1 / task #5)
# ---------------------------------------------------------------------------

def test_router_z_loss_shrinks_router_logits():
    """Training WITH the z-loss term must end with smaller router logit
    norms than training without (the ST-MoE stabilization claim) — the
    VERDICT 'done' criterion for the knob. Isolated to one MoE layer
    starting from a deliberately large-logit router so the contrast is
    deterministic (full-network SGD with an outsized z weight is exactly
    the instability the z-loss exists to prevent — see the 1e-3-typical
    weight on the CLI flag)."""
    def final_z(zw, steps=100, lr=0.05):
        params = _params()
        params["router"]["kernel"] = params["router"]["kernel"] * 200.0
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(2, 8, 16).astype(np.float32))
        target = jnp.asarray(rs.randn(2, 8, 16).astype(np.float32))

        @jax.jit
        def step(p):
            def loss(q):
                out, aux = moe.moe_ffn(q, x, n_experts=4,
                                       capacity_factor=8.0)
                return (jnp.mean(jnp.square(out - target))
                        + zw * aux["z_loss"])
            g = jax.grad(loss)(p)
            return jax.tree_util.tree_map(lambda a, b: a - lr * b, p, g)

        for _ in range(steps):
            params = step(params)
        _, aux = moe.moe_ffn(params, x, n_experts=4, capacity_factor=8.0)
        return float(aux["z_loss"])

    base = final_z(0.0)
    assert final_z(0.1) < 0.5 * base, base


def test_jitter_perturbs_routing_in_train_only():
    params = _params()
    rs = np.random.RandomState(7)
    x = jnp.asarray(rs.randn(2, 8, 16).astype(np.float32))
    base, _ = moe.moe_ffn(params, x, n_experts=4, capacity_factor=8.0)
    # no rng -> jitter is inert regardless of the knob
    off, _ = moe.moe_ffn(params, x, n_experts=4, capacity_factor=8.0,
                         jitter=0.5)
    np.testing.assert_array_equal(np.asarray(off), np.asarray(base))
    # rng + jitter -> routing (and thus the output) changes
    on, _ = moe.moe_ffn(params, x, n_experts=4, capacity_factor=8.0,
                        rng=jax.random.key(0), jitter=0.5)
    assert np.abs(np.asarray(on) - np.asarray(base)).max() > 0


def test_dropped_fraction_visible_at_tight_capacity():
    params = _params()
    params["router"]["kernel"] = jnp.zeros_like(params["router"]["kernel"])
    rs = np.random.RandomState(8)
    x = jnp.asarray(rs.randn(1, 8, 16).astype(np.float32))
    # zero router: all 8 tokens to expert 0; capacity_factor 0.5 -> C=1:
    # 7 of 8 assignments dropped
    _, aux = moe.moe_ffn(params, x, n_experts=4, capacity_factor=0.5)
    np.testing.assert_allclose(float(aux["dropped_fraction"]), 7 / 8)
    np.testing.assert_allclose(np.asarray(aux["expert_load"]),
                               [1.0, 0.0, 0.0, 0.0])
    # generous capacity drops nothing
    _, aux = moe.moe_ffn(params, x, n_experts=4, capacity_factor=8.0)
    assert float(aux["dropped_fraction"]) == 0.0


def test_moe_metrics_reach_the_stream():
    """MoeBert.loss surfaces routing health into the metrics dict: the
    scalars hooks print plus the full per-expert load vector."""
    m = _tiny_moe()
    params = m.init(jax.random.key(0))
    _, (metrics, _) = m.loss(params, {}, m.dummy_batch(2),
                             jax.random.key(1))
    for k in ("router_z_loss", "dropped_token_fraction",
              "expert_load_min", "expert_load_max"):
        assert np.ndim(metrics[k]) == 0, k
    assert metrics["expert_load"].shape == (m.cfg.n_experts,)
    assert float(metrics["expert_load_min"]) <=         float(metrics["expert_load_max"])


def test_new_moe_cli_knobs_reach_the_model():
    cfg = TrainConfig(model="moe_bert_tiny", moe_every=1,
                      moe_aux_weight=0.05, moe_router_z_weight=1e-3,
                      moe_jitter=0.01)
    m = get_model("moe_bert_tiny", cfg)
    assert m.cfg.moe_every == 1
    assert m.cfg.aux_weight == 0.05
    assert m.cfg.router_z_weight == 1e-3
    assert m.cfg.jitter == 0.01
    # moe_every=1 -> EVERY layer is MoE
    assert all(m._is_moe_layer(i) for i in range(m.cfg.layers))
    with pytest.raises(ValueError, match="moe_every"):
        get_model("moe_bert_tiny",
                  TrainConfig(model="moe_bert_tiny", moe_every=99))
    with pytest.raises(ValueError, match="moe_aux_weight"):
        get_model("moe_bert_tiny",
                  TrainConfig(model="moe_bert_tiny", moe_aux_weight=-1.0))
    with pytest.raises(ValueError, match="moe_router_z_weight"):
        get_model("moe_bert_tiny",
                  TrainConfig(model="moe_bert_tiny",
                              moe_router_z_weight=-0.1))
    with pytest.raises(ValueError, match="moe_jitter"):
        get_model("moe_bert_tiny",
                  TrainConfig(model="moe_bert_tiny", moe_jitter=1.5))


def test_moe_bert_trains_with_z_loss_and_jitter(cpu8):
    """The full recipe (z-loss + jitter + metrics) trains end to end and
    the vector metric survives the trainer's host conversion."""
    cfg = TrainConfig(model="moe_bert_tiny", moe_router_z_weight=1e-3,
                      moe_jitter=0.01)
    m = get_model("moe_bert_tiny", cfg)
    mesh = local_mesh(8, {"data": 2, "expert": 4})
    tx = make_optimizer(OptimizerConfig(name="adamw", learning_rate=1e-3))
    sync = SyncReplicas(m.loss, tx, mesh,
                        rules=m.sharding_rules(MeshShape(data=2, expert=4)))
    state = sync.init(m.init)
    batch = sync.shard_batch(m.dummy_batch(16))
    losses = []
    for _ in range(6):
        state, metrics = sync.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    host = {k: (float(v) if np.ndim(v) == 0 else np.asarray(v).tolist())
            for k, v in jax.device_get(metrics).items()}
    assert isinstance(host["expert_load"], list)
    assert len(host["expert_load"]) == 4


def test_moe_bert_composes_ep_with_fsdp(cpu8):
    """EP × fsdp composition ({data:2, fsdp:2, expert:2}): expert
    weights shard over `expert`, the big dense params (embeddings,
    attention kernels) shard over `fsdp`, and training still matches the
    fully-replicated single-axis run on the same global batch — the
    composition VERDICT r3 missing #1 called out as never exercised."""
    m = _tiny_moe()
    batch = m.dummy_batch(8)

    def run(mesh_shape, n):
        mesh = local_mesh(n, mesh_shape)
        mm = _tiny_moe()
        rules = mm.sharding_rules(MeshShape(**mesh_shape))
        tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
        sync = SyncReplicas(mm.loss, tx, mesh, rules=rules)
        state = sync.init(mm.init, seed=0)
        placed = sync.shard_batch(batch)
        losses = []
        for _ in range(3):
            state, metr = sync.step(state, placed)
            losses.append(float(metr["loss"]))
        return losses, state

    losses_c, state_c = run({"data": 2, "fsdp": 2, "expert": 2}, 8)
    losses_r, state_r = run({"data": 2}, 2)

    # same math, different layout: tight allclose (collective reduction
    # orders differ across meshes)
    np.testing.assert_allclose(losses_c, losses_r, rtol=1e-5, atol=1e-6)
    # the layout really is composed: expert weights on `expert`, the
    # word embedding on `fsdp`
    moe_w = state_c.params["layer_1"]["moe"]["w_in"]
    assert "expert" in str(moe_w.sharding.spec), moe_w.sharding
    emb = state_c.params["embed"]["word"]["table"]
    assert "fsdp" in str(emb.sharding.spec), emb.sharding
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        jax.device_get(state_c.params), jax.device_get(state_r.params))


# ---------------------------------------------------------------------------
# EP x TP (VERDICT r4 task #7): expert FFN kernels Megatron-split over
# `model` inside each expert, composing with the expert-axis exchange
# ---------------------------------------------------------------------------

def test_moe_shard_map_ep_x_tp_matches_dense(cpu8):
    """Explicit EP with model_axis set: tokens exchange over `expert`
    while each expert's FFN runs as a Megatron column/row split over
    `model` closed by a psum — output and aux must match the
    single-device dense path."""
    mesh = local_mesh(8, {"data": 2, "expert": 2, "model": 2})
    params = _params(n_experts=4, hidden=16, inter=32)
    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(4, 16, 16).astype(np.float32))
    dense, aux_d = moe.moe_ffn(params, x, n_experts=4,
                               capacity_factor=8.0)
    ep, aux_e = moe.moe_ffn_shard_map(params, x, mesh, n_experts=4,
                                      capacity_factor=8.0,
                                      batch_axes=("data",),
                                      model_axis="model")
    np.testing.assert_allclose(np.asarray(ep), np.asarray(dense),
                               rtol=1e-5, atol=1e-6)
    for k in ("lb_loss", "z_loss", "dropped_fraction"):
        np.testing.assert_allclose(float(aux_e[k]), float(aux_d[k]),
                                   rtol=1e-5, atol=1e-7, err_msg=k)


def test_moe_shard_map_ep_x_tp_grads_match_dense(cpu8):
    """Gradients through the EP x TP shard_map (all_to_all + psum both
    on the backward path) equal the dense path's."""
    mesh = local_mesh(4, {"expert": 2, "model": 2})
    params = _params(n_experts=4, hidden=16, inter=32, seed=5)
    rs = np.random.RandomState(6)
    x = jnp.asarray(rs.randn(2, 16, 16).astype(np.float32))

    def loss_dense(p):
        y, aux = moe.moe_ffn(p, x, n_experts=4, capacity_factor=8.0)
        return jnp.sum(y ** 2) + aux["lb_loss"]

    def loss_ep(p):
        y, aux = moe.moe_ffn_shard_map(p, x, mesh, n_experts=4,
                                       capacity_factor=8.0,
                                       batch_axes=(),
                                       model_axis="model")
        return jnp.sum(y ** 2) + aux["lb_loss"]

    gd = jax.jit(jax.grad(loss_dense))(params)
    ge = jax.jit(jax.grad(loss_ep))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5), ge, gd)


def test_moe_shard_map_tp_indivisible_is_loud(cpu8):
    mesh = local_mesh(4, {"expert": 2, "model": 2})
    params = _params(n_experts=4, hidden=16, inter=31)
    x = jnp.zeros((2, 16, 16), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        moe.moe_ffn_shard_map(params, x, mesh, n_experts=4,
                              batch_axes=(), model_axis="model")


def test_moe_bert_composes_ep_with_tp(cpu8):
    """The production (dense-dispatch GSPMD) path on a
    {data, expert, model} mesh: sharding rules put expert FFN kernels on
    BOTH axes (w_in [E, H, I/tp]), attention kernels on `model`, and
    training matches the single-axis replicated run."""
    m = _tiny_moe()
    batch = m.dummy_batch(8)

    def run(mesh_shape, n):
        mesh = local_mesh(n, mesh_shape)
        mm = _tiny_moe()
        rules = mm.sharding_rules(MeshShape(**mesh_shape))
        tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1))
        sync = SyncReplicas(mm.loss, tx, mesh, rules=rules)
        state = sync.init(mm.init, seed=0)
        placed = sync.shard_batch(batch)
        losses = []
        for _ in range(3):
            state, metr = sync.step(state, placed)
            losses.append(float(metr["loss"]))
        return losses, state

    losses_c, state_c = run({"data": 2, "expert": 2, "model": 2}, 8)
    losses_r, state_r = run({"data": 2}, 2)
    np.testing.assert_allclose(losses_c, losses_r, rtol=1e-5, atol=1e-6)
    w_in = state_c.params["layer_1"]["moe"]["w_in"]
    spec = str(w_in.sharding.spec)
    assert "expert" in spec and "model" in spec, w_in.sharding
    qk = state_c.params["layer_0"]["attn"]["q"]["kernel"]
    assert "model" in str(qk.sharding.spec), qk.sharding
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        jax.device_get(state_c.params), jax.device_get(state_r.params))
