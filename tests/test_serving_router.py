"""Replica router (round 15): breaker state machine, routing policy,
request-id propagation across failover, pushback propagation, fleet
observability, and the measured Retry-After seeding satellites.

The breaker tests run against an injected clock — no ``time.sleep``
drives any state transition in tier-1.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "experiments"))

import serving_chaos  # noqa: E402

from distributed_tensorflow_example_tpu.obs import prom  # noqa: E402
from distributed_tensorflow_example_tpu.obs.registry import (  # noqa: E402
    Registry, merge_snapshots)
from distributed_tensorflow_example_tpu.runtime import faults  # noqa: E402
from distributed_tensorflow_example_tpu.serving_router import (  # noqa: E402
    CircuitBreaker, ForwardError, InProcessFleet, Replica,
    ReplicaRouter)


@pytest.fixture(scope="module")
def fleet_dir(tmp_path_factory):
    """ONE tiny paged export shared by every HTTP-level router test."""
    d = str(tmp_path_factory.mktemp("router"))
    vocab = serving_chaos.build_chaos_export(d, seed=0)
    return d, vocab


def _wait(pred, timeout=30.0, what="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


def _post(port, name, payload, request_id=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/{name}:generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json",
                 **({"X-Request-Id": request_id} if request_id
                    else {})})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


# ---------------------------------------------------------------------------
# satellite: breaker state machine, deterministic via injected clock
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_breaker_opens_on_consecutive_threshold():
    clk = FakeClock()
    b = CircuitBreaker(threshold=3, cooldown_s=5.0, clock=clk)
    assert b.state == "closed" and b.allow()
    assert b.record_failure() is False
    assert b.record_failure() is False
    assert b.record_failure() is True        # 3rd consecutive: opens
    assert b.state == "open"
    assert not b.allow()                     # cooling down
    # a success resets the consecutive count while closed
    b2 = CircuitBreaker(threshold=3, cooldown_s=5.0, clock=clk)
    b2.record_failure()
    b2.record_failure()
    b2.record_success()
    assert b2.record_failure() is False and b2.state == "closed"


def test_breaker_opens_on_error_rate():
    clk = FakeClock()
    b = CircuitBreaker(threshold=100, error_rate=0.5, window=8,
                       min_samples=6, cooldown_s=5.0, clock=clk)
    # alternate success/failure: never 100 consecutive, but the window
    # hits 50% failures once min_samples exist
    opened = False
    for _ in range(4):
        b.record_success()
        opened = b.record_failure() or opened
    assert opened and b.state == "open"


def test_breaker_half_open_single_probe_then_close():
    clk = FakeClock()
    b = CircuitBreaker(threshold=1, cooldown_s=5.0, clock=clk)
    assert b.record_failure() is True and b.state == "open"
    assert not b.allow()                     # cooldown not elapsed
    clk.advance(5.1)
    assert b.allow()                         # THE half-open probe
    assert b.state == "half_open"
    assert not b.allow()                     # single probe at a time
    b.record_success()
    assert b.state == "closed" and b.allow()


def test_breaker_reopens_on_probe_failure():
    clk = FakeClock()
    b = CircuitBreaker(threshold=1, cooldown_s=5.0, clock=clk)
    b.record_failure()
    clk.advance(5.1)
    assert b.allow() and b.state == "half_open"
    assert b.record_failure() is True        # probe failed: re-open
    assert b.state == "open" and not b.allow()
    clk.advance(5.1)                         # cooldown restarted
    assert b.allow() and b.state == "half_open"


def test_breaker_rejects_bad_params():
    with pytest.raises(ValueError, match="threshold"):
        CircuitBreaker(threshold=0)
    with pytest.raises(ValueError, match="error_rate"):
        CircuitBreaker(error_rate=1.5)


# ---------------------------------------------------------------------------
# satellite: measured Retry-After on a predict-only replica
# ---------------------------------------------------------------------------

def test_microbatcher_retry_after_seeded_from_first_batch(tmp_path):
    """A replica that only ever serves ``:predict`` must NOT answer the
    1.0 pre-signal default forever: the estimator seeds from micro-
    batch wall time on the FIRST completed batch, so a later 429
    carries the measured estimate."""
    import jax

    from distributed_tensorflow_example_tpu.config import TrainConfig
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.serving import (
        export_model, load_servable, serving_signature)
    from distributed_tensorflow_example_tpu.serving_batch import (
        MicroBatcher, QueueFullError)
    d = str(tmp_path / "predict")
    m = get_model("mlp", TrainConfig(model="mlp"))
    out = m.init(jax.random.key(0))
    params, extras = out if isinstance(out, tuple) else (out, {})
    export_model(m, params, extras, d, platforms=("cpu",))
    feats = serving_signature(m.dummy_batch(4))
    x = np.asarray(feats["x"])
    mb = MicroBatcher(load_servable(d), batch_max_size=1,
                      batch_max_wait_ms=1.0, max_queue=2).start()
    try:
        assert not mb._retry.seeded
        # one COMPLETED batch seeds the estimator from wall time
        mb.submit({"x": x[:1]}, 1).result(timeout=60)
        _wait(lambda: mb._retry.seeded, what="estimator seeding")
        ema = mb._retry.ema_step_s
        assert ema is not None and ema > 0
        # wedge the dispatch so the queue fills, then assert the 429
        # hint is the MEASURED estimate, not the pre-signal 1.0
        wedged, release = threading.Event(), threading.Event()
        inner = mb.servable

        def wedge(cols):
            wedged.set()
            release.wait(timeout=60)
            return inner(cols)

        mb.servable = wedge
        try:
            futs = [mb.submit({"x": x[:1]}, 1)]
            assert wedged.wait(timeout=30)
            futs += [mb.submit({"x": x[:1]}, 1) for _ in range(2)]
            with pytest.raises(QueueFullError) as e:
                mb.submit({"x": x[:1]}, 1)
            expect = round(mb._retry.estimate(
                1.0, queue_ahead=2, slots=1), 2)
            assert e.value.retry_after == expect, \
                "429 hint is not the measured estimate"
        finally:
            release.set()
            for f in futs:
                f.result(timeout=60)
    finally:
        mb.close()


def test_replica_estimator_feeds_from_any_forward():
    """The router-side mirror of the same rule: a replica's wait hint
    is 0 (admissible) before any signal, and measured after ANY
    completed forward — :predict batches included."""
    r = Replica("http://127.0.0.1:9", name="p")
    assert r.wait_hint_s(outstanding=5) == 0.0     # no signal: admit
    r.observe(0.2)                                 # first completion
    assert r.retry.seeded
    assert r.wait_hint_s(outstanding=0) == pytest.approx(0.2)
    assert r.wait_hint_s(outstanding=3) == pytest.approx(0.8)


# ---------------------------------------------------------------------------
# routing policy units (no fleet, no start())
# ---------------------------------------------------------------------------

def _bare_router(n=3, **kw):
    reps = [Replica(f"http://127.0.0.1:{i + 1}", name=f"r{i}")
            for i in range(n)]
    router = ReplicaRouter(reps, name="m", **kw)
    for rep in reps:
        router._states[rep.name] = "healthy"
    return router, reps


def test_pick_least_outstanding_tie_breaks_by_order():
    router, reps = _bare_router(3)
    try:
        router._outstanding = {"r0": 2, "r1": 0, "r2": 1}
        assert router._pick(set(), None) is reps[1]
        router._outstanding = {"r0": 0, "r1": 0, "r2": 0}
        assert router._pick(set(), None) is reps[0]
        assert router._pick({"r0"}, None) is reps[1]
    finally:
        router.close()


def test_pick_skips_inadmissible_states_and_open_breakers():
    router, reps = _bare_router(3)
    try:
        router._states.update({"r0": "dead", "r1": "draining"})
        assert router._pick(set(), None) is reps[2]
        # breaker open and cooling: nothing admissible once r2 is out
        reps[2].breaker._state = "open"
        reps[2].breaker._opened_at = reps[2].breaker.clock()
        assert router._pick(set(), None) is None
        # cooldown elapsed: r2 is granted as the half-open trial
        reps[2].breaker._opened_at -= 100.0
        assert router._pick(set(), None) is reps[2]
        assert reps[2].breaker.state == "half_open"
    finally:
        router.close()


def test_pick_is_deadline_aware():
    """Never pick a replica whose measured queue wave exceeds the
    request's remaining deadline."""
    router, reps = _bare_router(2)
    try:
        reps[0].observe(0.5)                  # 500 ms measured wave
        router._outstanding = {"r0": 1, "r1": 3}
        # 200 ms left: r0's hint is 0.5*(1+1)=1000 ms -> skipped even
        # though it has fewer outstanding; r1 is unmeasured (hint 0)
        assert router._pick(set(), 200.0) is reps[1]
        # no deadline: least-outstanding wins as usual
        assert router._pick(set(), None) is reps[0]
        # both measured beyond the budget: nothing admissible
        reps[1].observe(0.5)
        assert router._pick(set(), 200.0) is None
    finally:
        router.close()


GEN_PATH = "/v1/models/m:generate"
GEN_PAYLOAD = {"inputs": {"input_ids": [[1, 2]]}}


def test_half_open_trial_pushback_releases_probe_slot():
    """Review regression: a half-open trial request that hits 429
    pushback must release the breaker's single probe slot (the replica
    answered — it is responsive), not quarantine the replica forever
    with allow() returning False for every future probe."""
    router, reps = _bare_router(1)
    try:
        rep = reps[0]
        rep.breaker = CircuitBreaker(threshold=1, cooldown_s=0.0)
        rep.breaker.record_failure()              # open, cooldown 0
        router._forward = lambda r, path, body, rid, t, trace=None: (
            429, {"Retry-After": "2"}, b'{"error": "full"}')
        st, headers, _ = router._serve(GEN_PATH, dict(GEN_PAYLOAD),
                                       "rid-po", True)
        assert st == 429 and headers["Retry-After"] == "2"
        # the trial released the slot AND counted as responsiveness:
        # the breaker is closed again, not wedged half-open
        assert rep.breaker.state == "closed"
        router._forward = lambda r, path, body, rid, t, trace=None: (
            200, {}, b'{"generations": [[9]]}')
        st, _, body = router._serve(GEN_PATH, dict(GEN_PAYLOAD),
                                    "rid-po2", True)
        assert st == 200
        assert json.loads(body)["served_by"] == "r0"
    finally:
        router.close()


def test_hedged_double_failure_excludes_both_replicas():
    """Review regression: when BOTH hedged attempts fail, the retry
    loop must not re-pick either of them — before the fix only the
    last-failing replica was excluded and the budget burned on a
    known-dead one."""
    router, reps = _bare_router(3, hedge_after_ms=10, retry_budget=2,
                                backoff_base_ms=1.0, backoff_cap_ms=2.0)
    try:
        calls = []

        def fake_forward(r, path, body, rid, timeout_s, trace=None):
            calls.append(r.name)
            if r.name == "r0":
                time.sleep(0.05)
                raise ForwardError(r, "conn reset")
            if r.name == "r1":
                raise ForwardError(r, "conn refused")
            return 200, {}, b'{"generations": [[7]]}'

        router._forward = fake_forward
        st, _, body = router._serve(GEN_PATH, dict(GEN_PAYLOAD),
                                    "rid-h2", True)
        assert st == 200
        assert json.loads(body)["served_by"] == "r2"
        # exactly one forward per replica: the post-hedge retry went
        # STRAIGHT to r2 instead of re-trying the failed hedge pair
        assert sorted(calls) == ["r0", "r1", "r2"], calls
        snap = router.registry.snapshot()
        assert snap["router_retries_total"]["value"] == 1
        assert snap["router_hedges_total"]["value"] == 1
    finally:
        router.close()


def test_float_deadline_ms_honored_and_decremented_on_failover():
    """Review regression: a float ``deadline_ms`` (any client doing
    wall-clock math sends one; the replica knob accepts it) must drive
    the router's deadline handling — before the fix it was silently
    ignored and every failover restarted the client's full budget."""
    router, _ = _bare_router(2, retry_budget=2, backoff_base_ms=1.0,
                             backoff_cap_ms=2.0)
    try:
        seen = []

        def fake_forward(r, path, body, rid, timeout_s, trace=None):
            seen.append(json.loads(body)["deadline_ms"])
            if len(seen) == 1:
                time.sleep(0.05)
                raise ForwardError(r, "conn reset")
            return 200, {}, b'{"generations": [[2]]}'

        router._forward = fake_forward
        st, _, _ = router._serve(
            GEN_PATH, {**GEN_PAYLOAD, "deadline_ms": 5000.0},
            "rid-fd", True)
        assert st == 200
        # every forward carries the REMAINING budget as an int, and
        # the failover's share is visibly smaller than the first's
        assert all(isinstance(d, int) for d in seen), seen
        assert seen[0] <= 5000
        assert seen[1] <= seen[0] - 50, seen
    finally:
        router.close()


def test_hedge_pushback_waits_for_sibling_never_cancels():
    """Review regression: a hedged wave whose primary answers 429 must
    wait for the in-flight sibling (which may win outright) instead of
    returning the pushback — and must never fire the async loser
    cancellation, which raced the same-rid retry and could cancel the
    client's fresh attempt."""
    router, reps = _bare_router(2, hedge_after_ms=10)
    try:
        cancels, calls = [], []
        router._cancel_on = lambda r, rids, ctx=None, parent_id=None: \
            cancels.append(r.name)

        def fake_forward(r, path, body, rid, timeout_s, trace=None):
            calls.append(r.name)
            if r.name == "r0":
                time.sleep(0.05)
                return 429, {"Retry-After": "2"}, b'{"error": "full"}'
            time.sleep(0.15)
            return 200, {}, b'{"generations": [[3]]}'

        router._forward = fake_forward
        st, _, body = router._serve(GEN_PATH, dict(GEN_PAYLOAD),
                                    "rid-hp", True)
        assert st == 200
        assert json.loads(body)["served_by"] == "r1"
        # exactly one forward per replica — the pushback neither
        # re-submitted the rid nor cancelled the winning sibling
        assert sorted(calls) == ["r0", "r1"], calls
        assert cancels == []
        # the pushback replica's breaker saw a response (responsive),
        # so a half-open trial slot could never leak here either
        assert reps[0].breaker.state == "closed"
    finally:
        router.close()


def test_hedge_winner_observes_its_own_wall_time():
    """Review regression: the hedge winner's estimator must be fed its
    OWN forward wall time — not the hedge delay plus the primary's
    wait, which would train the fastest replica's EMA toward
    hedge_after_ms and mis-steer the deadline-aware skip."""
    router, reps = _bare_router(2, hedge_after_ms=20)
    try:
        def fake_forward(r, path, body, rid, timeout_s, trace=None):
            time.sleep(0.3 if r.name == "r0" else 0.01)
            return 200, {}, b'{"generations": [[1]]}'

        router._forward = fake_forward
        st, _, body = router._serve(GEN_PATH, dict(GEN_PAYLOAD),
                                    "rid-hw", True)
        assert st == 200
        assert json.loads(body)["served_by"] == "r1"
        assert router.registry.snapshot()[
            "router_hedges_total"]["value"] == 1
        # the winner's EMA reflects its ~10 ms forward, not the
        # ~20 ms hedge delay + wait; the slow loser stays unobserved
        assert reps[1].retry.ema_step_s < 0.15
        assert reps[0].retry.ema_step_s is None
    finally:
        router.close()


# ---------------------------------------------------------------------------
# satellite: X-Request-Id end-to-end, surviving a failover
# ---------------------------------------------------------------------------

def test_request_id_survives_failover_retry(fleet_dir):
    """The SAME rid rides the retry onto the second replica after the
    first forward drops — and the response names the replica that
    actually served."""
    d, vocab = fleet_dir
    p = serving_chaos.seeded_prompts(1, 4, vocab)[0]
    faults.install(faults.parse_spec("router.forward:step=1", seed=0))
    try:
        with InProcessFleet(d, 2, probe_interval_s=0.05) as fleet:
            out = _post(fleet.port, fleet.name,
                        {"inputs": {"input_ids": [p.tolist()]},
                         "max_new": 3}, request_id="rid-failover")
            # first pick is replica0 (idle tie-break); its forward is
            # dropped by the seam, the retry lands on replica1
            assert out["request_ids"] == ["rid-failover"]
            assert out["served_by"] == "replica1"
            snap = fleet.router.registry.snapshot()
            assert snap["router_retries_total"]["value"] == 1
            assert snap["router_failovers_total"]["value"] == 1
            # the dropped forward fed replica0's breaker (one failure:
            # still closed at the default threshold)
            assert fleet.router.replicas[0].breaker.state == "closed"
    finally:
        faults.install(None)


def test_failover_bytes_match_direct_single_replica(fleet_dir):
    """Greedy output must be byte-identical no matter which replica
    serves or how many failovers occurred."""
    d, vocab = fleet_dir
    prompts = serving_chaos.seeded_prompts(2, 5, vocab)
    ref = serving_chaos.reference_run(d, prompts, max_new=4)
    faults.install(faults.parse_spec("router.forward:step=2", seed=0))
    try:
        with InProcessFleet(d, 2, probe_interval_s=0.05) as fleet:
            outs = [_post(fleet.port, fleet.name,
                          {"inputs": {"input_ids": [p.tolist()]},
                           "max_new": 4})["generations"][0]
                    for p in prompts]
            assert outs == ref
    finally:
        faults.install(None)


# ---------------------------------------------------------------------------
# pushback propagation + fleet observability
# ---------------------------------------------------------------------------

def test_pushback_propagates_with_min_retry_after(fleet_dir):
    """When EVERY admissible replica answers 429, the router
    propagates 429 with the smallest Retry-After seen."""
    from distributed_tensorflow_example_tpu.serving_batch import \
        QueueFullError
    d, _ = fleet_dir
    with InProcessFleet(d, 2, probe_interval_s=0.05) as fleet:
        def full_26(payload, request_id=None, trace=None):
            raise QueueFullError("full", retry_after=2.6)

        def full_71(payload, request_id=None, trace=None):
            raise QueueFullError("full", retry_after=7.1)

        fleet.servers[0].generate = full_26
        fleet.servers[1].generate = full_71
        try:
            _post(fleet.port, fleet.name,
                  {"inputs": {"input_ids": [[1, 2]]}})
            raise AssertionError("fleet-wide pushback not surfaced")
        except urllib.error.HTTPError as e:
            assert e.code == 429
            assert e.headers.get("Retry-After") == "3"   # min(2.6,7.1)
            assert "pushed back" in json.loads(e.read())["error"]


def test_fleet_metrics_merge_replica_pages(fleet_dir):
    """GET /metrics on the router merges every replica's exposition
    with the router's own registry through merge_snapshots; the first
    request also pins client X-Request-Id propagation end-to-end."""
    d, vocab = fleet_dir
    prompts = serving_chaos.seeded_prompts(3, 6, vocab)
    with InProcessFleet(d, 2, probe_interval_s=0.05) as fleet:
        out = _post(fleet.port, fleet.name,
                    {"inputs": {"input_ids": [prompts[0].tolist()]},
                     "max_new": 2}, request_id="rid-e2e")
        assert out["request_ids"] == ["rid-e2e"]
        assert out["timings"][0]["request_id"] == "rid-e2e"
        assert out["served_by"] in ("replica0", "replica1")
        for p in prompts[1:]:
            _post(fleet.port, fleet.name,
                  {"inputs": {"input_ids": [p.tolist()]}, "max_new": 2})
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fleet.port}/metrics",
                timeout=30) as r:
            merged = prom.parse(r.read().decode())
        # counters SUM across the fleet regardless of which replica
        # served which request
        assert merged["serving_requests_done_total"] == 3
        assert merged["router_requests_total"] == 3
        assert merged["router_replica_healthy"] == 2
        # histogram series merge too (count sums across replicas)
        assert merged["serving_request_latency_seconds_count"] == 3
        # /stats nests both replicas next to the router block
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fleet.port}/stats",
                timeout=30) as r:
            stats = json.loads(r.read())
        assert set(stats["replicas"]) == {"replica0", "replica1"}
        done = sum(rep["generate"]["requests_done"]
                   for rep in stats["replicas"].values())
        assert done == 3
        assert stats["router"]["requests"] == 3


def test_prom_parse_snapshot_roundtrip():
    """parse_snapshot is the exact inverse of render: a parsed page
    merges with the original snapshot (counters double, histogram
    buckets double, gauges hold)."""
    reg = Registry()
    reg.counter("rt_probe_total", "help text").inc(3)
    reg.gauge("rt_probe_depth").set(7)
    h = reg.histogram("rt_probe_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    snap = reg.snapshot()
    parsed = prom.parse_snapshot(prom.render(snap))
    assert parsed["rt_probe_total"] == {
        "type": "counter", "value": 3, "help": "help text"}
    assert parsed["rt_probe_depth"]["value"] == 7
    assert parsed["rt_probe_seconds"]["buckets"] == [(0.1, 1), (1.0, 1)]
    assert parsed["rt_probe_seconds"]["inf"] == 1
    assert parsed["rt_probe_seconds"]["count"] == 3
    merged = merge_snapshots(snap, parsed)
    assert merged["rt_probe_total"]["value"] == 6
    assert merged["rt_probe_depth"]["value"] == 7
    assert merged["rt_probe_seconds"]["count"] == 6
    prom.render(merged)                       # still renderable


def test_router_healthz_reflects_fleet(fleet_dir):
    d, _ = fleet_dir
    with InProcessFleet(d, 2, probe_interval_s=0.05,
                        dead_after_probes=2) as fleet:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fleet.port}/healthz",
                timeout=30) as r:
            assert r.status == 200
            body = json.loads(r.read())
        assert body["status"] == "live"
        assert {rep["state"] for rep in body["replicas"].values()} \
            == {"healthy"}
        fleet.crash(0)
        fleet.crash(1)
        _wait(lambda: all(
            s == "dead"
            for s in fleet.router.replica_states().values()),
            what="whole fleet marked dead")
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{fleet.port}/healthz", timeout=30)
            raise AssertionError("healthz stayed 200 with no replica")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.loads(e.read())["status"] == "unserved"


def test_router_cli_requires_replicas(capsys):
    from distributed_tensorflow_example_tpu import serving_router
    with pytest.raises(SystemExit):
        serving_router.main([])
    assert "--replica" in capsys.readouterr().err


def test_router_rejects_bad_config():
    with pytest.raises(ValueError, match="at least one"):
        ReplicaRouter([], name="m")
    with pytest.raises(ValueError, match="duplicate"):
        ReplicaRouter([Replica("http://a", name="x"),
                       Replica("http://b", name="x")], name="m")
    with pytest.raises(ValueError, match="retry_budget"):
        ReplicaRouter([Replica("http://a")], retry_budget=-1)
    with pytest.raises(ValueError, match="hedge_after_ms"):
        ReplicaRouter([Replica("http://a")], hedge_after_ms=-5)
