"""Worker for the two-process preemption / exact-resume test (not pytest).

Run as: python _two_process_preempt_worker.py <pid> <port> <outdir> <mode>

Modes (each a full process lifetime; the pytest driver runs them in
sequence, VERDICT r3 task #6):

- ``interrupted``: train toward step INTERRUPT_TARGET on a 2-process
  {data:2, fsdp:4} cluster; process 0 SIGTERMs ITSELF at step 3. The TSL
  preemption notifier (installed by jax.distributed.initialize) catches
  the signal, the coordination service broadcasts it, and
  PreemptionHook's ``reached_preemption_sync_point`` stops BOTH
  processes at the same step boundary (must land before TOTAL_STEPS),
  writes the final checkpoint, and exits 0.
- ``resume``: restart both processes on the same checkpoint dir; must
  restore at the stop step and continue to TOTAL_STEPS, recording
  per-step losses.
- ``straight``: an uninterrupted TOTAL_STEPS run in a fresh dir — the
  oracle the interrupted+resumed run must match bit-for-bit.
"""

import json
import os
import signal
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
from jax.experimental import multihost_utils

from distributed_tensorflow_example_tpu.cluster import ClusterSpec
from distributed_tensorflow_example_tpu.config import (CheckpointConfig,
                                                       DataConfig,
                                                       MeshShape,
                                                       OptimizerConfig,
                                                       TrainConfig)
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh
from distributed_tensorflow_example_tpu.runtime import distributed as rt
from distributed_tensorflow_example_tpu.train import hooks as hooks_lib
from distributed_tensorflow_example_tpu.train.trainer import Trainer

TOTAL_STEPS = 60
INTERRUPT_TARGET = 200    # far past the sync point: proves the stop fired
SIGTERM_AT = 3


def dataset():
    rs = np.random.RandomState(21)
    return {"x": rs.rand(640, 784).astype(np.float32),
            "y": rs.randint(0, 10, size=640).astype(np.int32)}


class _SigtermSelf(hooks_lib.Hook):
    """Deliver SIGTERM to THIS process at a step — caught by the TSL
    preemption notifier (C++), never by Python."""

    def __init__(self, at_step: int):
        self.at_step = at_step

    def after_step(self, trainer, step, metrics):
        if step == self.at_step:
            os.kill(os.getpid(), signal.SIGTERM)


class _RecordLosses(hooks_lib.Hook):
    def __init__(self):
        self.rows = []

    def wants_metrics(self, step):
        return True

    def after_step(self, trainer, step, metrics):
        self.rows.append((step, metrics["loss"]))


def main() -> int:
    pid = int(sys.argv[1])
    port = int(sys.argv[2])
    outdir = sys.argv[3]
    mode = sys.argv[4]

    cluster = ClusterSpec({"worker": [f"localhost:{port}",
                                      f"localhost:{port + 1}"]})
    rt.initialize(cluster, "worker", pid)
    assert jax.process_count() == 2

    ckpt_dir = os.path.join(
        outdir, "ckpt_straight" if mode == "straight" else "ckpt")
    steps = INTERRUPT_TARGET if mode == "interrupted" else TOTAL_STEPS
    cfg = TrainConfig(
        model="mlp", train_steps=steps, mesh=MeshShape(data=2, fsdp=4),
        data=DataConfig(batch_size=64, seed=5),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        checkpoint=CheckpointConfig(directory=ckpt_dir, save_steps=100),
        seed=13)
    data = dataset()
    model = get_model("mlp", cfg)
    rec = _RecordLosses()
    extra: list = [rec]
    if mode == "interrupted" and pid == 0:
        extra.append(_SigtermSelf(SIGTERM_AT))

    trainer = Trainer(model, cfg, {"x": data["x"], "y": data["y"]},
                      mesh=build_mesh(cfg.mesh), hooks=extra)
    state, summary = trainer.train()
    trainer.close()

    final_step = summary["final_step"]
    if mode == "interrupted":
        # the stop step floats (the protocol picks a boundary a few
        # steps after the signal — which may also arrive externally,
        # before the step-3 self-signal), but must land strictly below
        # TOTAL_STEPS or the resume run would have nothing left to do
        assert 0 < final_step < TOTAL_STEPS, (
            f"preemption sync point missing or too late "
            f"(final_step={final_step}, need < {TOTAL_STEPS})")
        rt.barrier("stop-save-done")   # proc 0 writes the checkpoint
        from distributed_tensorflow_example_tpu.ckpt.checkpoint import (
            CheckpointManager)
        assert CheckpointManager(ckpt_dir).latest_step() == final_step
    else:
        assert final_step == TOTAL_STEPS, summary

    params = [np.asarray(multihost_utils.process_allgather(p, tiled=True))
              for p in jax.tree_util.tree_leaves(state.params)]
    out = {f"p{i}": a for i, a in enumerate(params)}
    out["losses"] = np.asarray(rec.rows, np.float64)   # [K, (step, loss)]
    np.savez(os.path.join(outdir, f"{mode}_proc{pid}.npz"), **out)
    if pid == 0:
        with open(os.path.join(outdir, f"{mode}.json"), "w") as f:
            json.dump({"final_step": final_step}, f)
    rt.barrier(f"{mode}-done")
    print(f"proc {pid} mode {mode}: final_step={final_step}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
