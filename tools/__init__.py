"""Repo tooling (not shipped with the package): graftlint lives here."""
