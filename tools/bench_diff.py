"""bench_diff — machine-checkable comparison of two bench result files.

The BENCH_r01–r05 trajectory (and the bench gate itself) had no tool
answering "did anything regress between these two runs?" — reviewers
eyeballed JSON tails. This compares a baseline and a candidate file
key by key with a per-key relative tolerance and exits 1 on any
regression, so a TPU-window re-base (ROADMAP item 5) can gate on it:

    python tools/bench_diff.py BENCH_r04.json BENCH_r05.json
    python tools/bench_diff.py old.json new.json --tolerance 0.15 \
        --key gpt_serving_tps=0.3 --json

Accepted file shapes (auto-detected):

- a ``BENCH_rNN.json`` capture: ``{"n", "cmd", "rc", "tail"}`` where
  ``tail`` holds bench.py's JSON lines (``{"metric", "value",
  "extra": {...}}``) — metrics and their ``extra`` keys are flattened
  into one ``{key: value}`` table;
- a plain JSON object of numeric keys (a bench row, a summary line,
  ``bench_baseline.json``-style files; non-numeric values are
  ignored).

Direction is inferred from the key: ``*_ms`` / ``*_s`` / ``*_seconds``
/ ``*_errors`` / ``*_failures`` / ``*_dropped`` / ``*_drift_rate`` /
``*_bytes*`` are lower-is-better, everything else (tps, mfu,
eps_chip, rates, counts of useful work) higher-is-better; override
per key with ``--lower key`` / ``--higher key``. A key present in
only one file is reported (``missing_*``) but is not a regression —
new bench keys appear every few PRs and must not break the gate. A
zero baseline cannot anchor a relative tolerance, so it is reported
as ``zero_baseline`` and skipped.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any

LOWER_BETTER_MARKERS = ("_ms", "_s", "_seconds", "_errors",
                        "_failures", "_dropped", "_drift_rate")


def load_metrics(path: str) -> dict[str, float]:
    """One file -> flat ``{key: numeric value}`` (see module
    docstring for the accepted shapes)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object, got "
                         f"{type(doc).__name__}")
    out: dict[str, float] = {}
    if "tail" in doc and isinstance(doc["tail"], str):
        for line in doc["tail"].splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            if "metric" in rec and isinstance(
                    rec.get("value"), (int, float)):
                out[str(rec["metric"])] = float(rec["value"])
            extra = rec.get("extra")
            if isinstance(extra, dict):
                for k, v in extra.items():
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        out[str(k)] = float(v)
        if not out:
            raise ValueError(
                f"{path}: a tail-style capture with no parseable "
                "metric lines — nothing to compare")
        return out
    for k, v in doc.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[str(k)] = float(v)
    if not out:
        raise ValueError(f"{path}: no numeric keys to compare")
    return out


def lower_is_better(key: str) -> bool:
    # rates named *_per_s (tokens_per_s, requests_per_s — the serving
    # row shape) are throughput: the bare "_s" marker below must not
    # claim them as latencies
    if key.endswith("_per_s"):
        return False
    if "bytes" in key:
        return True
    return any(key.endswith(m) for m in LOWER_BETTER_MARKERS)


def diff(old: dict[str, float], new: dict[str, float], *,
         tolerance: float = 0.1,
         key_tolerance: dict[str, float] | None = None,
         force_lower: set[str] | None = None,
         force_higher: set[str] | None = None
         ) -> list[dict[str, Any]]:
    """Per-key comparison rows, regressions first then by key.

    A regression is a move in the key's WORSE direction by more than
    its relative tolerance: ``(new - old) / |old|`` above tol for
    lower-is-better keys, below -tol for higher-is-better keys."""
    key_tolerance = key_tolerance or {}
    force_lower = force_lower or set()
    force_higher = force_higher or set()
    rows: list[dict[str, Any]] = []
    for key in sorted(set(old) | set(new)):
        if key not in old:
            rows.append({"key": key, "status": "missing_old",
                         "new": new[key]})
            continue
        if key not in new:
            rows.append({"key": key, "status": "missing_new",
                         "old": old[key]})
            continue
        o, n = old[key], new[key]
        tol = key_tolerance.get(key, tolerance)
        if key in force_lower:
            lower = True
        elif key in force_higher:
            lower = False
        else:
            lower = lower_is_better(key)
        row = {"key": key, "old": o, "new": n,
               "lower_is_better": lower, "tolerance": tol}
        if o == 0.0:
            row["status"] = ("ok" if n == 0.0 else "zero_baseline")
            rows.append(row)
            continue
        rel = (n - o) / abs(o)
        row["delta_rel"] = round(rel, 6)
        worse = rel > tol if lower else rel < -tol
        better = rel < -tol if lower else rel > tol
        row["status"] = ("regression" if worse
                         else "improved" if better else "ok")
        rows.append(row)
    rows.sort(key=lambda r: (r["status"] != "regression", r["key"]))
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compare two bench result files; exit 1 on any "
                    "regression beyond tolerance")
    ap.add_argument("old", help="baseline file (BENCH_rNN.json or a "
                    "plain numeric JSON object)")
    ap.add_argument("new", help="candidate file")
    ap.add_argument("--tolerance", type=float, default=0.1,
                    help="default relative tolerance (0.1 = 10%%)")
    ap.add_argument("--key", action="append", default=[],
                    metavar="KEY=TOL",
                    help="per-key tolerance override (repeatable), "
                    "e.g. --key gpt_serving_tps=0.3")
    ap.add_argument("--lower", action="append", default=[],
                    help="force this key lower-is-better (repeatable)")
    ap.add_argument("--higher", action="append", default=[],
                    help="force this key higher-is-better (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full row table as JSON")
    args = ap.parse_args(argv)
    key_tol: dict[str, float] = {}
    for spec in args.key:
        k, sep, v = spec.partition("=")
        if not sep:
            ap.error(f"--key takes KEY=TOL, got {spec!r}")
        try:
            key_tol[k] = float(v)
        except ValueError:
            ap.error(f"--key {spec!r}: tolerance must be a number")
    try:
        old = load_metrics(args.old)
        new = load_metrics(args.new)
    except (OSError, ValueError) as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2
    rows = diff(old, new, tolerance=args.tolerance,
                key_tolerance=key_tol,
                force_lower=set(args.lower),
                force_higher=set(args.higher))
    regressions = [r for r in rows if r["status"] == "regression"]
    if args.json:
        print(json.dumps({"ok": not regressions,
                          "regressions": len(regressions),
                          "compared": sum(
                              1 for r in rows
                              if r["status"] not in ("missing_old",
                                                     "missing_new")),
                          "rows": rows}))
    else:
        for r in rows:
            if r["status"] in ("missing_old", "missing_new"):
                print(f"{r['status']:<13} {r['key']}")
                continue
            arrow = "v" if r["lower_is_better"] else "^"
            rel = r.get("delta_rel")
            rel_s = "     -" if rel is None else f"{100 * rel:+6.1f}%"
            print(f"{r['status']:<13} {r['key']:<44} "
                  f"{r['old']:>14g} -> {r['new']:>14g}  {rel_s} "
                  f"(better {arrow}, tol {r['tolerance']:g})")
        print(f"bench_diff: {len(regressions)} regression(s) in "
              f"{len(rows)} key(s)")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
