"""graftlint: invariant-checking static analysis for this repo.

``python -m tools.graftlint [--changed] [--json] [paths...]`` runs the
rule set (JIT01, DON01, THR01, OBS01, TRC01, CFG01 — see
:mod:`tools.graftlint.rules`) over the package and experiments; tier-1
requires a clean run (tests/test_graftlint.py).
"""

from .engine import (BASELINE_PATH, DEFAULT_ROOTS, SUPPRESSIONS_PATH,
                     Finding, LintResult, lint_paths, lint_source,
                     lint_sources, load_documented_suppressions,
                     load_files, suppression_inventory)
from .rules import ALL_RULES, RULES_BY_NAME

__all__ = [
    "ALL_RULES", "BASELINE_PATH", "DEFAULT_ROOTS", "Finding",
    "LintResult", "RULES_BY_NAME", "SUPPRESSIONS_PATH", "lint_paths",
    "lint_source", "lint_sources", "load_documented_suppressions",
    "load_files", "suppression_inventory",
]
