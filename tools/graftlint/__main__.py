"""CLI: ``python -m tools.graftlint [--changed] [--json] [paths...]``.

Exit codes: 0 = clean, 1 = findings, 2 = usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import engine


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description=("invariant-checking static analysis: JIT01 (jit "
                     "purity), DON01 (train-step donation), THR01 "
                     "(scheduler thread ownership), OBS01 (registered "
                     "metric names), TRC01 (declared span names), "
                     "CFG01 (dead config knobs). "
                     "Suppress one line with '# graftlint: "
                     "disable=RULE' plus a reason comment."))
    ap.add_argument("paths", nargs="*",
                    help="files/dirs relative to the repo root "
                    "(default: the package + experiments/)")
    ap.add_argument("--changed", action="store_true",
                    help="report only findings in files that differ "
                    "from git HEAD (analysis still covers the full "
                    "surface, so cross-file rules stay sound)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (one JSON object)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore tools/graftlint/baseline.json")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite baseline.json from the current "
                    "findings (emergency use; tier-1 pins it empty)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    from .rules import ALL_RULES
    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.name}  {r.doc}")
        return 0

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    try:
        result = engine.lint_paths(
            args.paths or None, rules=rules, changed=args.changed,
            use_baseline=not (args.no_baseline or args.write_baseline))
    except (ValueError, OSError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        entries = [f.as_dict() for f in result.findings]
        for e in entries:
            e.pop("line", None)
        with open(engine.BASELINE_PATH, "w", encoding="utf-8") as f:
            json.dump(entries, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"graftlint: wrote {len(entries)} baseline entr"
              f"{'y' if len(entries) == 1 else 'ies'} to "
              f"{engine.BASELINE_PATH}")
        return 0

    problems = result.parse_errors + result.findings
    if args.json:
        print(json.dumps({
            "findings": [f.as_dict() for f in problems],
            "suppressed": len(result.suppressed),
            "baselined": len(result.baselined),
            "files": result.files,
            "rules": result.rule_names,
            "per_rule": result.per_rule(),
            "clean": result.clean,
        }, indent=1, sort_keys=True))
    else:
        for f in problems:
            print(f.render())
        print(result.summary_line())
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
