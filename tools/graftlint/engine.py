"""graftlint engine: file discovery, rule driving, suppressions, baseline.

The repo's correctness contracts (jit purity, train-step donation, the
single-flight scheduler thread, registry-only metrics, no dead config
knobs) were enforced by convention plus one regression test each. This
engine machine-checks them: every rule in :mod:`tools.graftlint.rules`
walks the package's ASTs and reports :class:`Finding`\\ s; tier-1 runs
the whole lint and requires zero.

Escape hatches, in order of preference:

- fix the code (the default — a finding is a contract violation);
- a **commented suppression** on the offending line::

      self._live.clear()   # graftlint: disable=THR01  (thread joined)

  Every suppression site is inventoried and pinned by
  ``docs/graftlint_suppressions.txt`` — adding one without updating the
  inventory fails tier-1 loudly (tests/test_graftlint.py);
- the **baseline** (``tools/graftlint/baseline.json``): a list of
  finding fingerprints filtered from the report. It exists for
  emergencies (landing the lint over a tree with unfixable findings)
  and is guarded to stay EMPTY — prefer suppressions, which live next
  to the code they excuse.

Pure stdlib on purpose: the lint must run (and run fast) without a jax
backend, in CI, and inside the tier-1 terminal banner.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import subprocess
from typing import Iterable, Sequence

#: repo root = the directory holding tools/ (engine.py is tools/graftlint/)
REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

#: default lint surface: the package + the experiment harnesses
DEFAULT_ROOTS = ("distributed_tensorflow_example_tpu", "experiments")

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")

#: documented suppression inventory (the drift guard's pin — same
#: pattern as docs/known_failures.txt for the known-failure set)
SUPPRESSIONS_PATH = os.path.join(REPO_ROOT, "docs",
                                 "graftlint_suppressions.txt")

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    symbol: str        # enclosing qualname ("" = module level)
    message: str

    def fingerprint(self) -> tuple[str, str, str, str]:
        """Line-number-free identity (baseline matching must survive
        unrelated edits shifting lines)."""
        return (self.rule, self.path, self.symbol, self.message)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.rule}{sym} {self.message}"


@dataclasses.dataclass
class SourceFile:
    """One parsed lint input."""

    path: str                  # repo-relative
    src: str
    tree: ast.Module
    lines: list[str]

    @classmethod
    def from_source(cls, src: str, path: str) -> "SourceFile":
        return cls(path=path, src=src, tree=ast.parse(src),
                   lines=src.splitlines())


@dataclasses.dataclass
class LintResult:
    """Everything one lint run produced."""

    findings: list[Finding]
    suppressed: list[Finding]
    baselined: list[Finding]
    parse_errors: list[Finding]
    files: int
    rule_names: list[str]

    @property
    def clean(self) -> bool:
        return not self.findings and not self.parse_errors

    def per_rule(self) -> dict[str, int]:
        out = {name: 0 for name in self.rule_names}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def summary_line(self) -> str:
        counts = self.per_rule()
        hot = ", ".join(f"{r}={n}" for r, n in sorted(counts.items())
                        if n)
        total = len(self.findings) + len(self.parse_errors)
        line = (f"GRAFTLINT: {len(self.rule_names)} rule(s) over "
                f"{self.files} file(s), {total} finding(s)")
        if hot:
            line += f" ({hot})"
        line += (f", {len(self.suppressed)} suppression(s), "
                 f"baseline {len(self.baselined)}")
        return line


# ---------------------------------------------------------------------------
# file discovery
# ---------------------------------------------------------------------------

def _rel(path: str) -> str:
    return os.path.relpath(os.path.abspath(path),
                           REPO_ROOT).replace(os.sep, "/")


def iter_py_files(roots: Sequence[str] = DEFAULT_ROOTS) -> list[str]:
    """Repo-relative .py paths under ``roots`` (files or directories,
    given relative to the repo root), sorted for stable output."""
    out: list[str] = []
    for root in roots:
        full = os.path.join(REPO_ROOT, root)
        if os.path.isfile(full):
            if full.endswith(".py"):
                out.append(_rel(full))
            continue
        if not os.path.isdir(full):
            # a typo'd root must be LOUD: os.walk on a missing dir
            # yields nothing, and "0 file(s), 0 finding(s)" reads as a
            # green full lint having analyzed nothing
            raise ValueError(
                f"lint path {root!r} does not exist under the repo "
                "root — refusing to report a clean run over nothing")
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(_rel(os.path.join(dirpath, fn)))
    return sorted(set(out))


def changed_py_files(roots: Sequence[str] = DEFAULT_ROOTS) -> set[str]:
    """Repo-relative .py files under ``roots`` that differ from HEAD
    (staged, unstaged, or untracked) — the ``--changed`` report scope.
    Analysis always runs over the FULL surface (the cross-file rules
    need the whole registration/read universe); only reporting narrows.
    """
    names: set[str] = set()
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        # a git failure must be LOUD, not an empty set — an empty scope
        # would filter every finding and report a bogus clean run
        try:
            out = subprocess.run(cmd, cwd=REPO_ROOT, capture_output=True,
                                 text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise OSError(
                f"--changed needs git ({' '.join(cmd)} failed: {e}); "
                "run without --changed for the full report") from e
        if out.returncode != 0:
            raise OSError(
                f"--changed needs git ({' '.join(cmd)} exited "
                f"{out.returncode}: {out.stderr.strip()[:200]}); run "
                "without --changed for the full report")
        names.update(ln.strip() for ln in out.stdout.splitlines()
                     if ln.strip())
    # normalize roots the same way finding paths are normalized (_rel:
    # repo-relative, forward slashes) — git emits 'experiments/x.py',
    # so a './experiments' or absolute root must not silently empty the
    # scope and filter every finding into a bogus clean run
    norm = {_rel(os.path.join(REPO_ROOT, r)) for r in roots}
    prefixes = tuple(r + "/" for r in norm)
    return {n for n in names
            if n.endswith(".py")
            and (n.startswith(prefixes) or n in norm)}


def load_files(paths: Sequence[str] | None = None
               ) -> tuple[list[SourceFile], list[Finding]]:
    """Parse the lint surface; returns (files, parse_error_findings)."""
    files: list[SourceFile] = []
    errors: list[Finding] = []
    for rel in iter_py_files(paths or DEFAULT_ROOTS):
        full = os.path.join(REPO_ROOT, rel)
        with open(full, encoding="utf-8") as f:
            src = f.read()
        try:
            files.append(SourceFile.from_source(src, rel))
        except SyntaxError as e:
            errors.append(Finding(
                rule="PARSE", path=rel, line=e.lineno or 0, symbol="",
                message=f"file does not parse: {e.msg}"))
    return files, errors


# ---------------------------------------------------------------------------
# suppressions + baseline
# ---------------------------------------------------------------------------

def _suppressed_rules(line_text: str) -> set[str]:
    m = _SUPPRESS_RE.search(line_text)
    if not m:
        return set()
    return {r.strip() for r in m.group(1).split(",") if r.strip()}


def suppression_inventory(files: Iterable[SourceFile]
                          ) -> dict[tuple[str, str], int]:
    """{(path, rule): count} over every ``# graftlint: disable=`` comment
    in the tree — COMMENTS, not findings, so a suppression that no
    longer suppresses anything stays visible (and the drift guard makes
    its removal just as loud as an addition)."""
    inv: dict[tuple[str, str], int] = {}
    for sf in files:
        for text in sf.lines:
            for rule in _suppressed_rules(text):
                key = (sf.path, rule)
                inv[key] = inv.get(key, 0) + 1
    return inv


def load_documented_suppressions(path: str = SUPPRESSIONS_PATH
                                 ) -> dict[tuple[str, str], int]:
    """Parse docs/graftlint_suppressions.txt: ``<path> <RULE> <count>``
    per line, '#' comments skipped — THE parser, shared by the tier-1
    drift guard."""
    out: dict[tuple[str, str], int] = {}
    with open(path, encoding="utf-8") as f:
        for ln in f:
            ln = ln.strip()
            if not ln or ln.startswith("#"):
                continue
            parts = ln.split()
            if len(parts) != 3:
                raise ValueError(
                    f"bad suppression-inventory line {ln!r}: want "
                    "'<path> <RULE> <count>'")
            out[(parts[0], parts[1])] = int(parts[2])
    return out


def load_baseline(path: str = BASELINE_PATH) -> list[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# the run
# ---------------------------------------------------------------------------

def lint_files(files: list[SourceFile], *,
               rules: Sequence[str] | None = None,
               baseline: list[dict] | None = None,
               parse_errors: list[Finding] | None = None) -> LintResult:
    """Run the (sub)set of rules over already-parsed files."""
    from . import rules as rules_mod
    active = rules_mod.get_rules(rules)
    raw: list[Finding] = []
    for rule in active:
        raw.extend(rule.run(files))
    raw.sort(key=lambda f: (f.path, f.line, f.rule))

    by_path = {sf.path: sf for sf in files}
    live: list[Finding] = []
    suppressed: list[Finding] = []
    for f in raw:
        sf = by_path.get(f.path)
        text = (sf.lines[f.line - 1]
                if sf and 0 < f.line <= len(sf.lines) else "")
        rules_off = _suppressed_rules(text)
        if f.rule in rules_off or "all" in rules_off:
            suppressed.append(f)
        else:
            live.append(f)

    baselined: list[Finding] = []
    if baseline:
        # each baseline entry excuses at most ONE live finding (a
        # second identical violation is new work, not old debt)
        budget: dict[tuple, int] = {}
        for entry in baseline:
            key = (entry["rule"], entry["path"], entry.get("symbol", ""),
                   entry["message"])
            budget[key] = budget.get(key, 0) + 1
        still_live = []
        for f in live:
            k = f.fingerprint()
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                baselined.append(f)
            else:
                still_live.append(f)
        live = still_live

    return LintResult(findings=live, suppressed=suppressed,
                      baselined=baselined,
                      parse_errors=list(parse_errors or []),
                      files=len(files),
                      rule_names=[r.name for r in active])


def lint_paths(paths: Sequence[str] | None = None, *,
               rules: Sequence[str] | None = None,
               changed: bool = False,
               use_baseline: bool = True) -> LintResult:
    """Lint the repo surface (default: package + experiments).

    ``changed=True`` narrows the REPORT to files differing from HEAD;
    the analysis still covers the full surface so cross-file rules
    (OBS01 registrations, CFG01 reads) see everything.
    """
    files, parse_errors = load_files(paths)
    baseline = load_baseline() if use_baseline else None
    result = lint_files(files, rules=rules, baseline=baseline,
                        parse_errors=parse_errors)
    if changed:
        scope = changed_py_files(tuple(paths or DEFAULT_ROOTS))
        result.findings = [f for f in result.findings if f.path in scope]
        result.parse_errors = [f for f in result.parse_errors
                               if f.path in scope]
    return result


def lint_source(src: str, path: str = "<fixture>.py", *,
                rules: Sequence[str] | None = None) -> LintResult:
    """Lint one in-memory source blob (the test-fixture entry point —
    no baseline, no filesystem)."""
    return lint_sources({path: src}, rules=rules)


def lint_sources(sources: dict[str, str], *,
                 rules: Sequence[str] | None = None) -> LintResult:
    """Lint a dict of {path: source} in-memory files together (fixtures
    for the cross-file rules)."""
    files = [SourceFile.from_source(s, p) for p, s in sources.items()]
    return lint_files(files, rules=rules, baseline=None)
