"""The graftlint rule set — each rule encodes one existing repo contract.

=====  ====================================================================
rule   contract it machine-checks
=====  ====================================================================
JIT01  jit purity: no host syncs / wall clocks / metrics mutation inside
       jit-reachable code (the async-dispatch training loop and the
       compiled decode step both die by a thousand ``.item()`` cuts).
       An escape hatch exists: arguments of ``io_callback`` /
       ``pure_callback`` / ``jax.debug.callback`` run ON the host by
       design and are never flagged.
DON01  jitted train-step wrappers must DECLARE donation
       (``donate_argnums``/``donate_argnames``) — the static face of the
       tests/test_donation.py contract (~+1.3 GiB bert_long peak when
       donation is silently lost).
THR01  fields named by a ``@scheduler_owned(...)`` class marker may only
       be referenced from ``@scheduler_thread`` methods (full access),
       ``@snapshot_view`` methods (reads only — mutator calls like
       ``.clear()``, item writes, and attribute write-throughs count as
       writes), or ``__init__`` — the single-flight scheduler
       discipline of serving_batch.py, statically.
OBS01  every metric-name string literal must resolve to a registered
       ``counter()``/``gauge()``/``histogram()`` — the static inverse of
       the tier-1 dead-counter lint: that one catches registered-but-
       never-touched, this one catches a TYPO'D name (e.g. in a
       snapshot lookup) the runtime lint structurally cannot see.
TRC01  every span-name literal passed to ``span()``/``add_span()``
       must resolve against the declared span-name inventory
       (``docs/span_names.txt``, drift-guarded by
       tests/test_graftlint.py the way known_failures.txt is) — the
       fleet stitcher and the trace summaries group lanes by span
       NAME, so a typo'd name silently drops a span from every
       grouped view; OBS01's sibling for the trace vocabulary.
CFG01  config dataclass fields (config.py) and argparse ``--flags``
       declared but never read anywhere — a silently ignored knob is
       worse than an error (the repo's own config-validation mantra).
=====  ====================================================================

Every rule is heuristic where Python demands it (documented inline);
precision losses resolve through ``# graftlint: disable=RULE`` with a
comment, never by weakening the rule silently.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Sequence

from .engine import REPO_ROOT, Finding, SourceFile

# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------


def dotted(node: ast.AST) -> str | None:
    """'jax.jit' for Attribute chains / Names; None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last(name: str | None) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def _tokens(name: str) -> list[str]:
    return [t for t in name.split("_") if t]


def identifiers(node: ast.AST) -> set[str]:
    """Every Name id and Attribute attr inside an expression — the
    coarse 'which functions might this expression reference' set."""
    out: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def decorator_names(fn: ast.FunctionDef | ast.AsyncFunctionDef
                    ) -> set[str]:
    """Last-segment names of a def's decorators; for ``@partial(f, ...)``
    decorators the partial's first argument counts too."""
    out: set[str] = set()
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            name = _last(dotted(dec.func))
            out.add(name)
            if name == "partial" and dec.args:
                out.add(_last(dotted(dec.args[0])))
        else:
            out.add(_last(dotted(dec)))
    return out


def collect_aliases(tree: ast.Module) -> dict[str, set[str]]:
    """One-level local aliases: each single-target Assign maps the bound
    name to the identifiers of its RHS (``step_fn = self._auto_step``,
    ``f = a if cond else b``) — so ``jit(step_fn)`` still finds the def.
    Shared by JIT01 (reachability roots) and DON01 (call-site form)."""
    aliases: dict[str, set[str]] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name):
            aliases.setdefault(n.targets[0].id,
                               set()).update(identifiers(n.value))
    return aliases


def expand_aliases(names: set[str],
                   aliases: dict[str, set[str]]) -> set[str]:
    """Fixpoint-expand ``names`` through :func:`collect_aliases`' map."""
    seen, frontier = set(names), set(names)
    while frontier:
        nxt: set[str] = set()
        for nm in frontier:
            for extra in aliases.get(nm, ()):
                if extra not in seen:
                    seen.add(extra)
                    nxt.add(extra)
        frontier = nxt
    return seen


def walk_functions(tree: ast.Module):
    """Yield (qualname, node) for every function/method, depth-first."""
    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, child
                yield from visit(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, f"{prefix}{child.name}.")
            else:
                yield from visit(child, prefix)
    yield from visit(tree, "")


#: annotations that declare a parameter host-static: concretizing one
#: (float()/bool()) is legal even under jit — shape/config math, not a
#: tracer. Anything else (unannotated, Array, pytree, ...) stays suspect.
_STATIC_ANNOTATIONS = frozenset({"int", "float", "bool", "str", "bytes"})


def tracer_suspect_params(fn: ast.FunctionDef | ast.AsyncFunctionDef
                          ) -> set[str]:
    """Parameter names that might carry tracers: every param EXCEPT
    those annotated with a static scalar type (``capacity: int`` is
    host shape math by declaration)."""
    a = fn.args
    out: set[str] = set()
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        ann = p.annotation
        if ann is not None and _last(dotted(ann)) in _STATIC_ANNOTATIONS:
            continue
        out.add(p.arg)
    if a.vararg:
        out.add(a.vararg.arg)
    if a.kwarg:
        out.add(a.kwarg.arg)
    return out


class Rule:
    name = "RULE"
    doc = ""

    def run(self, files: list[SourceFile]) -> list[Finding]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# JIT01 — host sync / impurity inside jit-reachable code
# ---------------------------------------------------------------------------

#: transforms whose function arguments are TRACED (bare or dotted use)
JIT_WRAPPERS = frozenset({
    "jit", "pjit", "pmap", "vmap", "grad", "value_and_grad",
    "checkpoint", "remat", "custom_vjp", "custom_jvp", "pallas_call",
    "shard_map",
})

#: higher-order tracing ops — dotted use only (``lax.scan``): a bare
#: ``map``/``cond`` is far more likely the builtin / a local helper
TRACE_HOFS = frozenset({
    "scan", "while_loop", "cond", "switch", "fori_loop",
    "associative_scan", "map", "defvjp", "defjvp",
})

#: host-escape callbacks: their arguments run on the host BY DESIGN —
#: nothing inside them is a JIT01 violation (the documented hatch)
CALLBACK_ESCAPES = frozenset({"io_callback", "pure_callback", "callback"})

#: methods every registered model exposes to the jit'd trainer/exporter
#: (the Model protocol's traced surface) — roots even with no local
#: jit marker, so models/*.py is covered without cross-module analysis
MODEL_PROTOCOL_ROOTS = frozenset({"loss", "eval_metrics"})

#: path fragments whose every function is jit-reachable by contract:
#: ops/** is the kernel/op library — anything in it may be called
#: under jit, so all of it must stay pure
JIT_MODULE_FRAGMENTS = ("/ops/",)


class Jit01(Rule):
    name = "JIT01"
    doc = ("host sync / wall clock / metrics mutation inside "
           "jit-reachable code")

    def run(self, files):
        out: list[Finding] = []
        for sf in files:
            out.extend(self._check_file(sf))
        return out

    # -- reachability ---------------------------------------------------
    def _roots_and_defs(self, sf: SourceFile):
        defs: dict[str, list] = {}
        quals: dict[int, str] = {}
        for qual, fn in walk_functions(sf.tree):
            defs.setdefault(fn.name, []).append(fn)
            quals[id(fn)] = qual

        aliases = collect_aliases(sf.tree)
        roots: set[int] = set()

        def mark(names: Iterable[str]):
            for nm in expand_aliases(set(names), aliases):
                for fn in defs.get(nm, ()):
                    roots.add(id(fn))

        # 1) decorator-marked defs
        for fns in defs.values():
            for fn in fns:
                if decorator_names(fn) & JIT_WRAPPERS:
                    roots.add(id(fn))
        # 2) call-site-marked defs: jit(f) / lax.scan(body, ...)
        for n in ast.walk(sf.tree):
            if not isinstance(n, ast.Call):
                continue
            name = dotted(n.func)
            lastseg = _last(name)
            if lastseg in JIT_WRAPPERS or (
                    lastseg in TRACE_HOFS and name and "." in name):
                for arg in n.args:
                    mark(identifiers(arg))
        # 3) protocol + module-policy roots
        in_ops = any(frag in "/" + sf.path
                     for frag in JIT_MODULE_FRAGMENTS)
        for nm, fns in defs.items():
            if nm in MODEL_PROTOCOL_ROOTS or in_ops:
                roots.update(id(fn) for fn in fns)

        # 4) propagate through same-module calls: f() / self.f()
        reachable = set(roots)
        frontier = list(roots)
        by_id = {id(fn): fn for fns in defs.values() for fn in fns}
        while frontier:
            fn = by_id[frontier.pop()]
            for n in ast.walk(fn):
                if not isinstance(n, ast.Call):
                    continue
                callee = None
                if isinstance(n.func, ast.Name):
                    callee = n.func.id
                elif isinstance(n.func, ast.Attribute) and isinstance(
                        n.func.value, ast.Name) and n.func.value.id in (
                        "self", "cls"):
                    callee = n.func.attr
                if callee is None:
                    continue
                for target in defs.get(callee, ()):
                    if id(target) not in reachable:
                        reachable.add(id(target))
                        frontier.append(id(target))
        return reachable, quals, by_id

    # -- violation scan -------------------------------------------------
    def _check_file(self, sf: SourceFile) -> list[Finding]:
        reachable, quals, by_id = self._roots_and_defs(sf)
        out: list[Finding] = []
        # top-level reachable functions only: a nested reachable def is
        # scanned as part of its parent (param scopes stack)
        nested: set[int] = set()
        for fid in reachable:
            for n in ast.walk(by_id[fid]):
                if n is not by_id[fid] and isinstance(
                        n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nested.add(id(n))
        for fid in sorted(reachable - nested,
                          key=lambda i: by_id[i].lineno):
            fn = by_id[fid]
            self._scan(fn, sf, quals[fid], [tracer_suspect_params(fn)],
                       out)
        return out

    def _scan(self, node, sf, qual, param_stack, out):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan(child, sf, qual,
                           param_stack + [tracer_suspect_params(child)],
                           out)
                continue
            if isinstance(child, ast.Lambda):
                stack = param_stack + [{a.arg for a in (
                    child.args.args + child.args.kwonlyargs)}]
                body = child.body
                # the body EXPRESSION itself may be the offending call
                # (`lambda y: time.time()`): _scan only inspects
                # children, so check the root node here
                if isinstance(body, ast.Call):
                    if _last(dotted(body.func)) in CALLBACK_ESCAPES:
                        self._scan(body.func, sf, qual, stack, out)
                        continue
                    self._check_call(body, sf, qual, stack, out)
                self._scan(body, sf, qual, stack, out)
                continue
            if isinstance(child, ast.Call):
                if _last(dotted(child.func)) in CALLBACK_ESCAPES:
                    # the host-escape hatch: its args run host-side by
                    # design; only keep scanning the func expression
                    self._scan(child.func, sf, qual, param_stack, out)
                    continue
                self._check_call(child, sf, qual, param_stack, out)
            self._scan(child, sf, qual, param_stack, out)

    def _check_call(self, call: ast.Call, sf, qual, param_stack, out):
        def flag(msg):
            out.append(Finding(rule=self.name, path=sf.path,
                               line=call.lineno, symbol=qual,
                               message=msg))

        name = dotted(call.func)
        lastseg = _last(name)
        if isinstance(call.func, ast.Attribute):
            if lastseg == "item" and not call.args:
                flag("`.item()` forces a device->host sync inside "
                     "jit-reachable code")
                return
            if lastseg in ("inc", "observe"):
                flag(f"metrics mutation `.{lastseg}()` inside "
                     "jit-reachable code (registry counters are host "
                     "state; mutate them at the step boundary)")
                return
            if lastseg == "set":
                # x.at[i].set(v) is the functional array update — the
                # one `.set` that BELONGS in jit code
                recv = call.func.value
                at_update = (isinstance(recv, ast.Subscript)
                             and isinstance(recv.value, ast.Attribute)
                             and recv.value.attr == "at")
                if not at_update:
                    flag("`.set()` (gauge/metric mutation?) inside "
                         "jit-reachable code — only `.at[...].set()` "
                         "array updates belong here")
                return
        if name and name.startswith("time."):
            flag(f"`{name}()` reads the host wall clock inside "
                 "jit-reachable code (it evaluates ONCE at trace time)")
            return
        if name in ("jax.device_get", "device_get"):
            flag("`jax.device_get` inside jit-reachable code forces a "
                 "host sync")
            return
        if name and "." in name:
            base, attr = name.rsplit(".", 1)
            if base in ("np", "numpy") and attr in ("asarray", "array"):
                flag(f"`{name}()` materializes on host: on a tracer "
                     "this raises at runtime; use jnp instead")
                return
        if isinstance(call.func, ast.Name) and call.func.id in (
                "float", "bool") and len(call.args) == 1 \
                and isinstance(call.args[0], ast.Name):
            arg = call.args[0].id
            if any(arg in params for params in param_stack):
                flag(f"`{call.func.id}({arg})` on a traced argument "
                     "forces concretization (works only outside jit; "
                     "inside it raises TracerBoolConversionError)")


# ---------------------------------------------------------------------------
# DON01 — jitted train-step wrappers must declare donation
# ---------------------------------------------------------------------------

_DONATE_KWARGS = ("donate_argnums", "donate_argnames")


def _step_like(names: Iterable[str]) -> str | None:
    """The first identifier whose snake tokens include 'step' — the
    'this jit call wraps a train step' signal."""
    for nm in sorted(names):
        if "step" in _tokens(nm):
            return nm
    return None


class Don01(Rule):
    name = "DON01"
    doc = "jitted train-step wrappers must declare donation"

    def run(self, files):
        out: list[Finding] = []
        for sf in files:
            aliases = collect_aliases(sf.tree)

            for qual, fn in walk_functions(sf.tree):
                # decorator form: @jax.jit / @partial(jax.jit, ...)
                if "step" not in _tokens(fn.name):
                    continue
                for dec in fn.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    tname = _last(dotted(target))
                    jitlike = tname in ("jit", "pjit")
                    if isinstance(dec, ast.Call) and tname == "partial" \
                            and dec.args:
                        jitlike = _last(dotted(dec.args[0])) in ("jit",
                                                                 "pjit")
                    if not jitlike:
                        continue
                    kwargs = (
                        {kw.arg for kw in dec.keywords}
                        if isinstance(dec, ast.Call) else set())
                    if not kwargs & set(_DONATE_KWARGS):
                        out.append(Finding(
                            rule=self.name, path=sf.path, line=fn.lineno,
                            symbol=qual, message=self._msg(fn.name)))
                    break
            for n in ast.walk(sf.tree):
                if not isinstance(n, ast.Call) \
                        or _last(dotted(n.func)) not in ("jit", "pjit") \
                        or not n.args:
                    continue
                wrapped = _step_like(
                    expand_aliases(identifiers(n.args[0]), aliases))
                if wrapped is None:
                    continue
                if not {kw.arg for kw in n.keywords} & set(_DONATE_KWARGS):
                    out.append(Finding(
                        rule=self.name, path=sf.path, line=n.lineno,
                        symbol="", message=self._msg(wrapped)))
        return out

    @staticmethod
    def _msg(name: str) -> str:
        return (f"jit of step-like `{name}` declares no donate_argnums/"
                "donate_argnames — losing TrainState donation costs "
                "~+1.3 GiB peak on bert_long (tests/test_donation.py "
                "contract); declare donation (an empty tuple is an "
                "explicit, visible choice)")


# ---------------------------------------------------------------------------
# THR01 — scheduler-owned fields vs thread-marked methods
# ---------------------------------------------------------------------------

#: container/attribute mutators a @snapshot_view method must not call on
#: an owned field — a `self._live.clear()` keeps the attribute itself in
#: Load context, so ctx alone cannot see the write (and the runtime
#: sanitizer's read allowance equally lets the load through; this static
#: check is the only layer that catches mutation-through-method)
_VIEW_MUTATORS = frozenset({
    "clear", "pop", "popitem", "update", "setdefault", "append",
    "extend", "insert", "remove", "add", "discard", "sort", "reverse",
    "appendleft", "extendleft", "popleft", "__setitem__", "__delitem__",
})


class Thr01(Rule):
    name = "THR01"
    doc = ("@scheduler_owned fields only from @scheduler_thread methods "
           "or @snapshot_view reads")

    def run(self, files):
        out: list[Finding] = []
        for sf in files:
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    owned = self._owned_fields(node)
                    if owned:
                        out.extend(self._check_class(sf, node, owned))
        return out

    @staticmethod
    def _owned_fields(cls: ast.ClassDef) -> frozenset[str]:
        for dec in cls.decorator_list:
            if isinstance(dec, ast.Call) and _last(
                    dotted(dec.func)) == "scheduler_owned":
                return frozenset(
                    a.value for a in dec.args
                    if isinstance(a, ast.Constant)
                    and isinstance(a.value, str))
        return frozenset()

    def _check_class(self, sf, cls, owned):
        out: list[Finding] = []
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if item.name == "__init__":
                continue          # construction precedes the thread
            decs = decorator_names(item)
            full = "scheduler_thread" in decs
            read_only = "snapshot_view" in decs
            if full:
                continue
            parents = {child: parent for parent in ast.walk(item)
                       for child in ast.iter_child_nodes(parent)}
            for n in ast.walk(item):
                if not (isinstance(n, ast.Attribute)
                        and isinstance(n.value, ast.Name)
                        and n.value.id == "self"
                        and n.attr in owned):
                    continue
                qual = f"{cls.name}.{item.name}"
                if read_only:
                    how = self._view_mutation(n, parents)
                    if how is None and isinstance(n.ctx, ast.Load):
                        continue
                    out.append(Finding(
                        rule=self.name, path=sf.path, line=n.lineno,
                        symbol=qual,
                        message=(f"@snapshot_view method writes "
                                 f"scheduler-owned field `{n.attr}`"
                                 + (f" ({how})" if how else "")
                                 + " — views read, only "
                                 "@scheduler_thread methods mutate")))
                else:
                    out.append(Finding(
                        rule=self.name, path=sf.path, line=n.lineno,
                        symbol=qual,
                        message=(f"scheduler-owned field `{n.attr}` "
                                 f"referenced from `{item.name}`, which "
                                 "is neither @scheduler_thread nor "
                                 "@snapshot_view — only the scheduler "
                                 "thread owns this state")))
        return out

    @staticmethod
    def _view_mutation(n: ast.Attribute, parents: dict) -> str | None:
        """Mutation of an owned field whose attribute node itself sits
        in Load context: ``self._live.clear()`` (mutator call),
        ``self._live[k] = v`` / ``del self._live[k]`` (item write), and
        ``self.blocks.x = v`` (write-through) all load `self.<field>`
        first — ctx alone cannot see them. Returns a short description
        of the mutation, or None for a genuine read."""
        p = parents.get(n)
        if isinstance(p, ast.Subscript) and p.value is n \
                and not isinstance(p.ctx, ast.Load):
            return "item assignment through the view"
        if isinstance(p, ast.Attribute) and p.value is n:
            if not isinstance(p.ctx, ast.Load):
                return f"write through `.{p.attr}`"
            gp = parents.get(p)
            if isinstance(gp, ast.Call) and gp.func is p \
                    and p.attr in _VIEW_MUTATORS:
                return f"mutating call `.{p.attr}()`"
        return None


# ---------------------------------------------------------------------------
# OBS01 — metric-name literals must resolve to a registered metric
# ---------------------------------------------------------------------------

_METRIC_NAME_RE = re.compile(r"[a-z][a-z0-9]*(?:_[a-z0-9]+)+")
_REGISTER_METHODS = ("counter", "gauge", "histogram")


class Obs01(Rule):
    name = "OBS01"
    doc = "metric-name string literals must resolve to a registered metric"

    def run(self, files):
        registered: set[str] = set()
        register_calls: list[tuple[SourceFile, ast.Call]] = []
        for sf in files:
            for n in ast.walk(sf.tree):
                if isinstance(n, ast.Call) and isinstance(
                        n.func, ast.Attribute) \
                        and n.func.attr in _REGISTER_METHODS \
                        and n.args and isinstance(n.args[0], ast.Constant) \
                        and isinstance(n.args[0].value, str):
                    registered.add(n.args[0].value)
                    register_calls.append((sf, n))
        if not registered:
            return []
        # the naming convention is self-calibrating: the first and last
        # snake tokens of REGISTERED names define what "looks like a
        # metric name" (e.g. serving_* ... *_total) — so `train_x`
        # (a data key) never trips the rule, while a typo'd
        # `serving_decode_stepz_total` does
        prefixes = {_tokens(r)[0] for r in registered}
        suffixes = {_tokens(r)[-1] for r in registered}
        skip_spans: dict[str, list[tuple[int, int]]] = {}
        for sf, call in register_calls:
            skip_spans.setdefault(sf.path, []).append(
                (call.lineno, call.end_lineno or call.lineno))

        out: list[Finding] = []
        for sf in files:
            spans = skip_spans.get(sf.path, [])
            # docstrings / bare string statements are prose — collect
            # their Constant nodes first (skipping the ast.Expr in the
            # walk would NOT skip the Constant inside it)
            prose: set[int] = set()
            for n in ast.walk(sf.tree):
                if isinstance(n, ast.Expr) and isinstance(
                        n.value, ast.Constant):
                    prose.add(id(n.value))
            for n in ast.walk(sf.tree):
                if id(n) in prose:
                    continue
                if not (isinstance(n, ast.Constant)
                        and isinstance(n.value, str)):
                    continue
                s = n.value
                if s in registered or not _METRIC_NAME_RE.fullmatch(s):
                    continue
                toks = _tokens(s)
                if toks[0] not in prefixes or toks[-1] not in suffixes:
                    continue
                if any(a <= n.lineno <= b for a, b in spans):
                    continue
                out.append(Finding(
                    rule=self.name, path=sf.path, line=n.lineno,
                    symbol="",
                    message=(f"metric name {s!r} is never registered "
                             "with counter()/gauge()/histogram() — a "
                             "typo'd name the runtime dead-counter lint "
                             "cannot see (it only knows names that DO "
                             "get registered)")))
        return out


# ---------------------------------------------------------------------------
# TRC01 — span-name literals must resolve against docs/span_names.txt
# ---------------------------------------------------------------------------

#: the declared span-name inventory (drift-guarded by
#: tests/test_graftlint.py exactly like docs/known_failures.txt)
SPAN_NAMES_PATH = os.path.join(REPO_ROOT, "docs", "span_names.txt")

#: the span-recording entry points; BARE-name calls only — attribute
#: calls like a regex match's ``m.span(1)`` are a different function
_SPAN_FNS = frozenset({"span", "add_span"})


def load_span_inventory(path: str = SPAN_NAMES_PATH) -> set[str]:
    """docs/span_names.txt: one span name per line, '#' comments
    skipped — THE parser, shared with the tier-1 drift guard."""
    with open(path, encoding="utf-8") as f:
        return {ln.strip() for ln in f
                if ln.strip() and not ln.startswith("#")}


def collect_span_literals(files: Iterable[SourceFile]
                          ) -> dict[str, list[tuple[str, int]]]:
    """{span name -> [(path, line), ...]} over every statically-visible
    span name: the literal FIRST argument of a bare ``span()`` /
    ``add_span()`` call, a literal ``span_name=`` keyword argument, and
    a ``span_name`` parameter's literal default (the engine's
    decode/verify dispatch passes its span name through that
    parameter). Variable names are skipped — a heuristic documented on
    the rule; the drift guard keeps the inventory honest from the
    other side."""
    out: dict[str, list[tuple[str, int]]] = {}

    def add(name: str, sf: SourceFile, line: int) -> None:
        out.setdefault(name, []).append((sf.path, line))

    for sf in files:
        for n in ast.walk(sf.tree):
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Name) \
                        and n.func.id in _SPAN_FNS and n.args \
                        and isinstance(n.args[0], ast.Constant) \
                        and isinstance(n.args[0].value, str):
                    add(n.args[0].value, sf, n.args[0].lineno)
                # the router's span wrapper: _rspan(ctx, rid, NAME,
                # t0, t1, ...) — a span-recording entry point too
                if _last(dotted(n.func)) == "_rspan" \
                        and len(n.args) >= 3 \
                        and isinstance(n.args[2], ast.Constant) \
                        and isinstance(n.args[2].value, str):
                    add(n.args[2].value, sf, n.args[2].lineno)
                for kw in n.keywords:
                    if kw.arg == "span_name" and isinstance(
                            kw.value, ast.Constant) and isinstance(
                            kw.value.value, str):
                        add(kw.value.value, sf, kw.value.lineno)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = n.args
                params = a.posonlyargs + a.args + a.kwonlyargs
                defaults = ([None] * (len(a.posonlyargs + a.args)
                                      - len(a.defaults))
                            + list(a.defaults) + list(a.kw_defaults))
                for p, d in zip(params, defaults):
                    if p.arg == "span_name" and isinstance(
                            d, ast.Constant) and isinstance(
                            d.value, str):
                        add(d.value, sf, d.lineno)
    return out


class Trc01(Rule):
    name = "TRC01"
    doc = ("span-name literals must resolve against the "
           "docs/span_names.txt inventory")

    def run(self, files):
        try:
            inventory = load_span_inventory()
        except OSError as e:
            return [Finding(
                rule=self.name, path="docs/span_names.txt", line=0,
                symbol="",
                message=f"span-name inventory unreadable ({e}) — the "
                        "rule cannot resolve any span() name")]
        out: list[Finding] = []
        for name, sites in sorted(collect_span_literals(files).items()):
            if name in inventory:
                continue
            for path, line in sites:
                out.append(Finding(
                    rule=self.name, path=path, line=line, symbol="",
                    message=(f"span name {name!r} is not in "
                             "docs/span_names.txt — the stitcher and "
                             "trace summaries group lanes by span "
                             "name, so a typo'd name silently drops "
                             "the span from every grouped view; add "
                             "it to the inventory (and the drift "
                             "guard) or fix the typo")))
        return out


# ---------------------------------------------------------------------------
# CFG01 — config fields / CLI flags declared but never read
# ---------------------------------------------------------------------------

class Cfg01(Rule):
    name = "CFG01"
    doc = "config fields / CLI flags declared but never read"

    def run(self, files):
        declared: list[tuple[SourceFile, int, str, str]] = []
        reads: set[str] = set()
        for sf in files:
            is_config = sf.path.endswith("config.py")
            for n in ast.walk(sf.tree):
                if isinstance(n, ast.Attribute) and isinstance(
                        n.ctx, ast.Load):
                    reads.add(n.attr)
                elif isinstance(n, ast.Call):
                    fname = dotted(n.func)
                    if isinstance(n.func, ast.Name) \
                            and n.func.id == "getattr" \
                            and len(n.args) >= 2 and isinstance(
                                n.args[1], ast.Constant):
                        reads.add(str(n.args[1].value))
                    elif _last(fname) == "add_argument" and n.args \
                            and isinstance(n.args[0], ast.Constant) \
                            and isinstance(n.args[0].value, str) \
                            and n.args[0].value.startswith("--"):
                        dest = n.args[0].value.lstrip("-").replace(
                            "-", "_")
                        for kw in n.keywords:
                            if kw.arg == "dest" and isinstance(
                                    kw.value, ast.Constant):
                                dest = str(kw.value.value)
                        declared.append((sf, n.lineno, "flag",
                                         dest))
                elif is_config and isinstance(n, ast.ClassDef) \
                        and self._is_dataclass(n):
                    for st in n.body:
                        if isinstance(st, ast.AnnAssign) and isinstance(
                                st.target, ast.Name):
                            declared.append(
                                (sf, st.lineno, f"{n.name} field",
                                 st.target.id))
        out: list[Finding] = []
        for sf, line, kind, name in declared:
            if name in reads:
                continue
            what = ("config " + kind if kind.endswith("field")
                    else f"CLI flag --{name}")
            out.append(Finding(
                rule=self.name, path=sf.path, line=line, symbol="",
                message=(f"{what} ({name!r}) is declared but never "
                         "read anywhere in the package or experiments "
                         "— a silently ignored knob is worse than an "
                         "error: wire it up or delete it")))
        return out

    @staticmethod
    def _is_dataclass(cls: ast.ClassDef) -> bool:
        for dec in cls.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if _last(dotted(target)) == "dataclass":
                return True
        return False


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

ALL_RULES: tuple[Rule, ...] = (Jit01(), Don01(), Thr01(), Obs01(),
                               Trc01(), Cfg01())
RULES_BY_NAME = {r.name: r for r in ALL_RULES}


def get_rules(names: Sequence[str] | None = None) -> list[Rule]:
    if names is None:
        return list(ALL_RULES)
    unknown = sorted(set(names) - set(RULES_BY_NAME))
    if unknown:
        raise ValueError(f"unknown rule(s) {unknown}; have "
                         f"{sorted(RULES_BY_NAME)}")
    return [RULES_BY_NAME[n] for n in names]
