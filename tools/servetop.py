"""servetop — live/offline SLO & goodput view of a serving process.

``top`` for the serving fleet: polls ``GET /stats/history`` on a
replica (serving_http.py) or a router (serving_router.py — the fleet
rollup) and renders per-class attainment, error-budget burn, goodput
vs raw throughput, queue pressure, and the per-replica breakdown.
Offline mode renders a dumped payload file instead — incident triage
reads the ``history_tail`` of an ``slo_burn`` bundle the same way.

    python tools/servetop.py --url http://127.0.0.1:8501            # live
    python tools/servetop.py --url ... --frames 1                   # one frame
    python tools/servetop.py --file history.json                    # offline
    python tools/servetop.py --file history.json --json             # machine

Everything is computed from the payload's ``[t, snapshot]`` samples
through the pure window queries (obs/timeseries.py) and reported
exactly as the registry counted it — :func:`compute_summary` is the
function the ``slo_report`` smoke leg reconciles against the harness
ledger and the request-log replay, so it must add nothing of its own.
Rates/quantiles are windowed (``--window``, default the whole ring);
attainment is the good/served ratio over the same window.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from distributed_tensorflow_example_tpu.obs import (  # noqa: E402
    timeseries as ts)

#: priority classes rendered, best first (mirrors serving_batch)
CLASSES = ("interactive", "batch", "best_effort")

#: brownout rung names (mirrors serving_batch.PRESSURE_STATES)
PRESSURE_STATES = ("healthy", "shed_best_effort", "shed_batch",
                   "interactive_only")


def _last(samples, name, default=0):
    """Newest sample's scalar value for ``name``."""
    if not samples:
        return default
    rec = samples[-1][1].get(name)
    return rec["value"] if rec and "value" in rec else default


def _class_block(win, cls: str) -> dict:
    served = ts.delta(win, f"serving_slo_served_{cls}_total")
    good = ts.delta(win, f"serving_slo_good_{cls}_total")
    return {
        "served": served,
        "good": good,
        "attainment": round(good / served, 6) if served else None,
        "shed": ts.delta(win, f"serving_shed_{cls}_total"),
        "p95_ms": round(
            ts.quantile(win, f"serving_latency_{cls}_seconds", 0.95)
            * 1e3, 3),
    }


def compute_summary(payload: dict, *,
                    window_s: float | None = None) -> dict:
    """One frame's numbers from a ``/stats/history`` payload — pure
    (no clocks, no network), so the smoke leg can reconcile it
    EXACTLY against the harness ledger and the request-log replay."""
    samples = ts.parse_payload(payload)
    win = ts.window(samples, window_s)
    summary = {
        "enabled": bool(payload.get("enabled", bool(samples))),
        "process": payload.get("process", "?"),
        "samples": len(samples),
        "window_s": round(ts.duration_s(win), 3),
        "throughput_tps": round(
            ts.rate_per_s(win, "serving_tokens_out_total"), 3),
        "goodput_tps": round(
            ts.rate_per_s(win, "serving_goodput_tokens_total"), 3),
        "requests_per_s": round(
            ts.rate_per_s(win, "serving_slo_served_total"), 3),
        "served": ts.delta(win, "serving_slo_served_total"),
        "good": ts.delta(win, "serving_slo_good_total"),
        "goodput_tokens": ts.delta(win,
                                   "serving_goodput_tokens_total"),
        "tokens": ts.delta(win, "serving_tokens_out_total"),
        "shed": ts.delta(win, "serving_shed_total"),
        "queue_depth": _last(samples, "serving_queue_depth"),
        "queue_age_s": _last(samples, "serving_queue_age_seconds"),
        "pressure": PRESSURE_STATES[
            min(int(_last(samples, "serving_pressure_level")),
                len(PRESSURE_STATES) - 1)],
        "classes": {cls: _class_block(win, cls) for cls in CLASSES},
        "slo": (payload.get("slo") or {}).get("results"),
    }
    replicas = payload.get("replicas")
    if isinstance(replicas, dict):
        summary["replicas"] = {}
        for name, rp in sorted(replicas.items()):
            if not isinstance(rp, dict) or "error" in rp:
                summary["replicas"][name] = {
                    "error": (rp or {}).get("error", "no payload")}
                continue
            rs = ts.parse_payload(rp)
            rwin = ts.window(rs, window_s)
            served = ts.delta(rwin, "serving_slo_served_total")
            good = ts.delta(rwin, "serving_slo_good_total")
            summary["replicas"][name] = {
                "throughput_tps": round(
                    ts.rate_per_s(rwin, "serving_tokens_out_total"),
                    3),
                "goodput_tps": round(
                    ts.rate_per_s(rwin,
                                  "serving_goodput_tokens_total"), 3),
                "served": served,
                "attainment": round(good / served, 6) if served
                else None,
                "queue_depth": _last(rs, "serving_queue_depth"),
                "clock_offset_s": rp.get("clock_offset_s", 0.0),
            }
    return summary


def _fmt_ratio(v) -> str:
    return "   -  " if v is None else f"{100 * v:5.1f}%"


def render(summary: dict) -> str:
    """One text frame. Deliberately plain (no cursor tricks): pipes,
    logs, and tests read it as-is."""
    if not summary.get("enabled"):
        return (f"servetop: {summary.get('process', '?')}: history "
                "sampler is off (start the server with "
                "--history_interval_s > 0)")
    lines = [
        f"servetop — {summary['process']}  "
        f"[{summary['samples']} samples, window "
        f"{summary['window_s']}s]",
        f"  throughput {summary['throughput_tps']:9.2f} tok/s   "
        f"goodput {summary['goodput_tps']:9.2f} tok/s   "
        f"requests {summary['requests_per_s']:7.2f}/s",
        f"  served {summary['served']}  good {summary['good']}  "
        f"shed {summary['shed']}  queue {summary['queue_depth']} "
        f"(age {summary['queue_age_s']}s)  "
        f"pressure {summary['pressure']}",
        "  class         served   good   shed  attain    p95_ms",
    ]
    for cls in CLASSES:
        b = summary["classes"][cls]
        lines.append(
            f"  {cls:<12} {b['served']:7} {b['good']:6} "
            f"{b['shed']:6}  {_fmt_ratio(b['attainment'])} "
            f"{b['p95_ms']:9.3f}")
    if summary.get("slo"):
        lines.append("  objective                 attain  "
                     "burn_fast  burn_slow  state")
        for r in summary["slo"]:
            name = f"{r['class']}:{r['kind']}"
            lines.append(
                f"  {name:<25} {_fmt_ratio(r['attainment'])} "
                f"{r['burn_fast']:10.2f} {r['burn_slow']:10.2f}  "
                f"{'BREACH' if r['breach'] else 'ok'}")
    if summary.get("replicas"):
        lines.append("  replica       tok/s   goodput  served  "
                     "attain  queue  clk_off_s")
        for name, b in summary["replicas"].items():
            if "error" in b:
                lines.append(f"  {name:<12} ERROR {b['error']}")
                continue
            lines.append(
                f"  {name:<12} {b['throughput_tps']:7.2f} "
                f"{b['goodput_tps']:9.2f} {b['served']:7}  "
                f"{_fmt_ratio(b['attainment'])} "
                f"{b['queue_depth']:6} {b['clock_offset_s']:10.6f}")
    return "\n".join(lines)


def fetch(url: str, timeout: float = 10.0) -> dict:
    """One ``GET <url>/stats/history`` poll (the URL may also point
    straight at the endpoint)."""
    if not url.rstrip("/").endswith("/stats/history"):
        url = url.rstrip("/") + "/stats/history"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="live/offline SLO & goodput view over "
                    "GET /stats/history")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", help="replica or router base URL "
                     "(e.g. http://127.0.0.1:8501)")
    src.add_argument("--file", help="offline: render a dumped "
                     "/stats/history payload (or an slo_burn "
                     "bundle's history_tail wrapped as "
                     "{'samples': [...]})")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll cadence in seconds (live mode)")
    ap.add_argument("--frames", type=int, default=0,
                    help="stop after N frames (0 = until Ctrl-C; "
                    "--file always renders exactly one)")
    ap.add_argument("--window", type=float, default=0.0,
                    help="rate/attainment window in seconds "
                    "(0 = the whole ring)")
    ap.add_argument("--json", action="store_true",
                    help="emit the computed summary as JSON instead "
                    "of the text frame")
    args = ap.parse_args(argv)
    window_s = args.window or None

    def emit(payload) -> None:
        s = compute_summary(payload, window_s=window_s)
        print(json.dumps(s) if args.json else render(s), flush=True)

    if args.file:
        with open(args.file) as f:
            emit(json.load(f))
        return 0
    frames = 0
    try:
        while True:
            emit(fetch(args.url))
            frames += 1
            if args.frames and frames >= args.frames:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
